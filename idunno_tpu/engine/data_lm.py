"""LM training data pipeline: tokenized corpus → per-process sharded batches.

The reference moves its (image) dataset to workers over SDFS before
inference (`README.md:37-38`); the LM-training analogue stores the tokenized
corpus in the replicated file layer (`idunno_tpu.store`), and every training
process loads it once and draws its OWN disjoint shard of each epoch —
deterministic from (seed, epoch), so data parallelism across
`jax.distributed` processes needs no coordination traffic at all.

TPU-first shape discipline: every batch is exactly [batch, seq_len + 1]
int32 (inputs = [:, :-1], targets = [:, 1:] — or feed the full block to
`train_lm`'s roll-based loss); the ragged tail of an epoch is dropped so
jit never sees a new shape.
"""
from __future__ import annotations

from collections.abc import Iterator

import numpy as np

from idunno_tpu.store.sdfs import FileStoreService

_DTYPE = np.int32


def save_corpus(store: FileStoreService, name: str,
                tokens: np.ndarray) -> int:
    """Version a tokenized corpus (1-D int array) into the replicated
    store; returns the store version."""
    arr = np.ascontiguousarray(tokens, dtype=_DTYPE)
    return store.put_bytes(name, arr.tobytes())


def load_corpus(store: FileStoreService, name: str) -> np.ndarray:
    """Fetch the latest corpus version from any node."""
    blob, _ = store.get_bytes(name)
    return np.frombuffer(blob, dtype=_DTYPE)


class TokenDataset:
    """Fixed-length block sampler over a token stream.

    Blocks are the ``n // (seq_len+1)`` non-overlapping windows; each epoch
    visits every block exactly once in a seeded shuffle, partitioned
    round-robin across processes (process p takes blocks p, p+P, p+2P, ...
    of the permutation — equal counts, disjoint, union = epoch).
    """

    def __init__(self, tokens: np.ndarray, seq_len: int, *,
                 seed: int = 0) -> None:
        self.tokens = np.ascontiguousarray(tokens, dtype=_DTYPE)
        self.seq_len = seq_len
        self.seed = seed
        self.block = seq_len + 1
        self.n_blocks = len(self.tokens) // self.block
        if self.n_blocks == 0:
            raise ValueError(f"corpus of {len(self.tokens)} tokens is "
                             f"shorter than one {self.block}-token block")

    def epoch_blocks(self, epoch: int, *, process_index: int = 0,
                     process_count: int = 1) -> np.ndarray:
        """This process's block indices for ``epoch`` (deterministic).
        The permutation is truncated to a multiple of process_count so every
        process gets the SAME shard length — unequal lengths would leave one
        process alone inside a collective-bearing train step (SPMD hang)."""
        rng = np.random.default_rng((self.seed, epoch))
        perm = rng.permutation(self.n_blocks)
        usable = self.n_blocks - self.n_blocks % process_count
        return perm[:usable][process_index::process_count]

    def batches(self, batch_size: int, epoch: int = 0, *,
                process_index: int = 0,
                process_count: int = 1) -> Iterator[np.ndarray]:
        """Yield [batch_size, seq_len+1] int32 arrays; ragged tail dropped
        (static shapes for jit)."""
        idx = self.epoch_blocks(epoch, process_index=process_index,
                                process_count=process_count)
        view = self.tokens[:self.n_blocks * self.block].reshape(
            self.n_blocks, self.block)
        for i in range(0, len(idx) - batch_size + 1, batch_size):
            yield view[idx[i:i + batch_size]]
