"""Paged KV-cache block pool (vLLM PagedAttention-style, PAPERS.md).

The decode-cache leaves (`cached_k`/`cached_v`, plus `k_scale`/`v_scale`
on int8 caches — `models/transformer.py`) are contiguous per sequence:
token position t lives at index t of the cache's token axis. That layout
is what the serving tier's static-shape programs want, but it makes KV
reuse all-or-nothing — the single pool-level `prefix=` cache in
`engine/serve_lm.py` is paid once at pool build and shared by every
request, and nothing else is ever reused.

This module adds the missing granularity: a pool of fixed-size TOKEN
BLOCKS over the same leaves. Each block holds `block_size` consecutive
token positions of every K/V leaf; a prompt's KV is then a CHAIN of
blocks that other requests with the same token prefix (at the same
absolute positions) can splice into their own prefill via the existing
`_prefill_suffix` path. Ownership/eviction policy lives one level up in
`serve/prefix_cache.py` (the radix tree); this pool only does storage:

  alloc/free     — free-list, O(1), no compaction (blocks are uniform)
  incref/decref  — per-block reference counts: a block is pinned while
                   any admitted request's chain holds it, so the tree
                   can only evict refcount-0 chains
  write_block    — copy one block's worth of a prefill row cache's K/V
                   into a block (one compiled scatter per leaf shape;
                   the block id and token offset are traced, so block
                   churn never recompiles)
  gather         — assemble a chain back into a batch-1, length-n·bs
                   cache tree whose leaf paths match `init_cache`'s, so
                   `_prefill_suffix` can splice it verbatim

Correctness note: the transformer is causal, so a token's K/V depends
only on the tokens at and before its position — KV written by ONE
request is bit-identical to what any other request with the same token
prefix (and the same pool-level static prefix ahead of it) would
compute at those positions. That is the whole reason cross-request
sharing can keep greedy decode token-exact (`tests/test_prefix_cache.py`
pins this against `engine/generate.py`).

The block stores are allocated unsharded by default (replicated under a
mesh): blocks are batch-1 slivers the admission path gathers/scatters on
the host-facing side of the pool; the big [slots, max_len] decode cache
in `DecodeServer` keeps its mesh sharding unchanged. Under tensor
parallelism (``mesh=`` with a "model" axis of extent > 1) the stores
shard their KV-head dim over the model axis — matching the decode
cache's head split, so the paged kernel's page reads stay chip-local —
while the block axis stays whole on every chip (the host-side free-list
addresses any block from anywhere).

The reference has no KV reuse at any granularity — every query
recomputes from scratch (`mp4_machinelearning.py:541-616`).
"""
from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from idunno_tpu.engine.generate import init_cache

# cache leaves that carry per-token K/V state (int8 caches add scales);
# must stay in lockstep with `serve_lm._prefill_suffix`'s splice filter
KV_LEAF_KEYS = ("cached_k", "cached_v", "k_scale", "v_scale")


def _is_kv(path) -> bool:
    return bool(path) and getattr(path[-1], "key", None) in KV_LEAF_KEYS


@partial(jax.jit, static_argnames=("stacked",))
def _write_block(store: jnp.ndarray, row_leaf: jnp.ndarray,
                 bid: jnp.ndarray, off: jnp.ndarray,
                 stacked: bool = False) -> jnp.ndarray:
    """store[bid] = row_leaf[0, off:off+block_size]. bid/off are traced:
    one compile per (store shape, row length), not per block or offset.
    ``stacked`` is a STATIC flag, not rank-inferred: a scanned-cache
    k_scale leaf [L, 1, T, kvh] has the same rank as an unscanned
    cached_k [1, T, h, d], so only the caller knows the layout."""
    if stacked:
        # row_leaf is [L, 1, T, ...]; block slivers keep the depth axis.
        # Stacked stores are [L, N, bs, ...] (depth LEADS, block second)
        # so the paged decode path can hand `store[l]` — a ready-made
        # [N, bs, ...] page array — to the per-layer scan body with no
        # moveaxis/copy (ops/paged_attention.py).
        bs = store.shape[2]
        chunk = jax.lax.dynamic_slice_in_dim(row_leaf[:, 0], off, bs,
                                             axis=1)
        return store.at[:, bid].set(chunk.astype(store.dtype))
    bs = store.shape[1]
    chunk = jax.lax.dynamic_slice_in_dim(row_leaf[0], off, bs, axis=0)
    return store.at[bid].set(chunk.astype(store.dtype))


@partial(jax.jit, static_argnames=("n", "stacked"))
def _gather_blocks(store: jnp.ndarray, bids: jnp.ndarray,
                   n: int, stacked: bool = False) -> jnp.ndarray:
    """[n blocks] → one contiguous leaf: [1, n·block_size, ...] per-block,
    [L, 1, n·block_size, ...] stacked (depth leads, batch-1 second)."""
    if stacked:
        picked = store[:, bids]                    # [L, n, bs, ...]
        return picked.reshape(
            (store.shape[0], 1, n * store.shape[2]) + store.shape[3:])
    return store[bids].reshape((1, n * store.shape[1]) + store.shape[2:])


def concat_kv_prefix(front: Any, back: Any, token_axis: int = 1) -> Any:
    """Concatenate two batch-1 cache trees along the token axis at the
    K/V leaves (static pool prefix + gathered radix chain → one combined
    prefix for `_prefill_suffix`). Non-K/V leaves (cursors) are taken
    from ``front`` — the consumer overwrites them anyway. Leaves match
    by keystr path, not container identity, so a flax-mutated cache and
    an `init_cache` template compose regardless of dict flavor.
    ``token_axis`` is 1 for the per-block layout, 2 for depth-stacked
    scanned caches ([L, 1, T, ...])."""
    src = {jax.tree_util.keystr(p): leaf for p, leaf
           in jax.tree_util.tree_flatten_with_path(back)[0] if _is_kv(p)}

    def f(path, x):
        if _is_kv(path):
            return jnp.concatenate(
                [x, src[jax.tree_util.keystr(path)]], axis=token_axis)
        return x
    return jax.tree_util.tree_map_with_path(f, front)


class KVBlockPool:
    """Fixed-size token-block storage over a model's decode-cache K/V
    leaves, with free-list allocation and per-block refcounts. Policy-
    free: see `serve/prefix_cache.py` for the radix tree that decides
    what the blocks mean and when they are evicted."""

    def __init__(self, model, num_blocks: int, block_size: int,
                 mesh=None) -> None:
        if num_blocks < 1:
            raise ValueError(f"num_blocks {num_blocks} must be >= 1")
        if block_size < 1:
            raise ValueError(f"block_size {block_size} must be >= 1")
        self.model = model
        self.num_blocks = num_blocks
        self.block_size = block_size
        # TP page sharding: shard the KV-head dim of every store over the
        # mesh's "model" axis when the heads divide (mirrors the decode
        # cache's split — `parallel/sharding.py:lm_cache_specs`); a
        # non-dividing head count replicates, same as no mesh at all
        self._head_shard = None
        if mesh is not None:
            from idunno_tpu.parallel.mesh import MODEL_AXIS
            n_model = int(mesh.shape.get(MODEL_AXIS, 1))
            kvh = getattr(model, "num_kv_heads", None) or model.num_heads
            if n_model > 1 and kvh % n_model == 0:
                self._head_shard = (mesh, n_model)
        # scanned models carry depth-stacked caches ([L, 1, bs, ...]);
        # the stores lead with the depth axis ([L, N, bs, ...]) so one
        # write/gather moves every layer's sliver at once AND store[l]
        # is directly the per-layer page array the paged kernel reads
        self._stacked = bool(getattr(model, "scan_layers", False))
        # batch-1 length-block_size template names the K/V leaves and
        # their per-token shapes; the stores add a leading block axis
        shapes = jax.eval_shape(lambda: init_cache(model, 1, block_size))
        self._stores: dict[str, jnp.ndarray] = {}
        # leaf NAME ("cached_k", …) → store keystr, for kv_pages(); a
        # stacked pool has exactly one cache leaf per name, unscanned
        # pools have one per layer (name collisions → kv_pages refuses)
        self._leaf_names: dict[str, str | None] = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(shapes)[0]:
            if _is_kv(path):
                if self._stacked:
                    # depth LEADS: [L, N, bs, ...] — store[l] is the
                    # per-layer page array the paged kernel consumes
                    shape = ((leaf.shape[0], num_blocks, block_size)
                             + leaf.shape[3:])
                else:
                    shape = (num_blocks, block_size) + leaf.shape[2:]
                key = jax.tree_util.keystr(path)
                name = path[-1].key
                self._stores[key] = self._alloc_store(shape, leaf.dtype,
                                                      name)
                self._leaf_names[name] = (
                    None if name in self._leaf_names else key)
        if not self._stores:
            raise ValueError("model's decode cache has no K/V leaves")
        self._free: list[int] = list(range(num_blocks - 1, -1, -1))
        self._refs: dict[int, int] = {}       # allocated block → refcount
        # eval_shape templates for gather output trees, keyed by length
        self._tree_templates: dict[int, Any] = {}

    def _alloc_store(self, shape: tuple, dtype, name: str) -> jnp.ndarray:
        """Zeroed store, head-sharded over the model axis under TP. The
        KV-head dim is second-to-last on cached_k/v ([.., kvh, d]) and
        last on the scale leaves ([.., kvh])."""
        if self._head_shard is None:
            return jnp.zeros(shape, dtype)
        from jax.sharding import NamedSharding, PartitionSpec
        from idunno_tpu.parallel.mesh import MODEL_AXIS
        mesh, _ = self._head_shard
        head_dim = len(shape) - (2 if name in ("cached_k", "cached_v")
                                 else 1)
        axes = [None] * len(shape)
        axes[head_dim] = MODEL_AXIS
        sh = NamedSharding(mesh, PartitionSpec(*axes))
        return jax.jit(lambda: jnp.zeros(shape, dtype),
                       out_shardings=sh)()

    # -- allocation -------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self.num_blocks - len(self._free)

    def alloc(self) -> int | None:
        """One free block (refcount 0) or None when the pool is full —
        the caller decides whether to evict or skip."""
        if not self._free:
            return None
        bid = self._free.pop()
        self._refs[bid] = 0
        return bid

    def free(self, bid: int) -> None:
        refs = self._refs.get(bid)
        if refs is None:
            raise ValueError(f"block {bid} is not allocated")
        if refs:
            # refused free must leave the block tracked (still allocated)
            raise ValueError(f"block {bid} freed with refcount {refs}")
        del self._refs[bid]
        self._free.append(bid)

    def incref(self, bid: int) -> None:
        self._refs[bid] += 1

    def decref(self, bid: int) -> None:
        if self._refs[bid] < 1:
            raise ValueError(f"block {bid} decref below zero")
        self._refs[bid] -= 1

    def refcount(self, bid: int) -> int:
        return self._refs[bid]

    # -- data movement ----------------------------------------------------

    def write_block(self, bid: int, row_cache: Any, offset: int) -> None:
        """Copy token positions [offset, offset+block_size) of a batch-1
        prefill cache's K/V leaves into block ``bid``. The offset is an
        ABSOLUTE cache position — with a pool-level static prefix ahead
        of the request tokens, the caller passes prefix_len + i.

        The window must lie inside the row cache: `dynamic_slice` clamps
        out-of-range starts SILENTLY, which would duplicate the tail
        block's tokens into the next block and poison every later prefix
        hit — so out-of-range offsets raise here instead."""
        src = {jax.tree_util.keystr(p): leaf for p, leaf
               in jax.tree_util.tree_flatten_with_path(row_cache)[0]
               if _is_kv(p)}
        tok_axis = 2 if self._stacked else 1
        row_len = next(iter(src.values())).shape[tok_axis]
        if offset < 0 or offset + self.block_size > row_len:
            raise ValueError(
                f"write_block offset {offset} + block_size "
                f"{self.block_size} outside row cache of {row_len} "
                f"tokens (offset is an ABSOLUTE cache position — did the "
                f"caller forget/double-count the static prefix length?)")
        b = jnp.int32(bid)
        off = jnp.int32(offset)
        for key, store in self._stores.items():
            self._stores[key] = _write_block(store, src[key], b, off,
                                             stacked=self._stacked)

    def read_block(self, bid: int) -> dict[str, Any]:
        """One block's raw per-leaf content as HOST numpy arrays, keyed
        by leaf keystr — the payload half of a cluster prefix-cache
        publish (`serve/cluster_prefix.py`). Stacked pools return
        ``[L, bs, ...]`` slivers, unscanned ``[bs, ...]``. Under TP the
        read gathers the head-sharded store — logical shapes (and
        bytes) are identical across ``n_model``, so published blobs are
        content-equal regardless of the publisher's mesh."""
        if bid not in self._refs:
            raise ValueError(f"block {bid} is not allocated")
        out = {}
        for key, store in self._stores.items():
            sliver = store[:, bid] if self._stacked else store[bid]
            out[key] = np.asarray(jax.device_get(sliver))
        return out

    def write_raw_block(self, bid: int, arrays: dict[str, Any]) -> None:
        """Inverse of `read_block`: install fetched raw slivers into
        block ``bid``. Every store leaf must be present with its exact
        per-block shape — a partial or mis-shaped payload raises before
        any store is touched (a half-written block would poison every
        later prefix hit on its chain)."""
        if bid not in self._refs:
            raise ValueError(f"block {bid} is not allocated")
        staged = {}
        for key, store in self._stores.items():
            arr = arrays.get(key)
            want = store.shape[:1] + store.shape[2:] if self._stacked \
                else store.shape[1:]
            if arr is None:
                raise ValueError(f"write_raw_block missing leaf {key!r}")
            if tuple(arr.shape) != tuple(want):
                raise ValueError(
                    f"write_raw_block leaf {key!r} shape {arr.shape} != "
                    f"store block shape {want}")
            staged[key] = jnp.asarray(arr, store.dtype)
        for key, store in self._stores.items():
            if self._stacked:
                self._stores[key] = store.at[:, bid].set(staged[key])
            else:
                self._stores[key] = store.at[bid].set(staged[key])

    def kv_pages(self) -> dict[str, jnp.ndarray]:
        """Raw page stores by leaf name ({"cached_k", "cached_v"} plus
        {"k_scale", "v_scale"} on int8 pools), each ``[L, N, bs, ...]``
        — the arrays the paged decode path (`ops/paged_attention.py`)
        reads THROUGH the block table instead of gathering. Stacked
        (scanned) pools only: an unscanned multi-layer pool has one
        store per layer under the same leaf name, which has no single
        per-name page array to hand out."""
        if not self._stacked:
            raise ValueError(
                "kv_pages() requires a depth-stacked (scanned) pool; "
                "unscanned pools keep the gather path")
        out = {}
        for name, key in self._leaf_names.items():
            if key is None:
                raise ValueError(
                    f"ambiguous page store for leaf {name!r} "
                    f"(per-layer leaves collide)")
            out[name] = self._stores[key]
        return out

    @property
    def bytes_per_block(self) -> int:
        """Bytes one block occupies across every K/V leaf store — the
        unit of the `kv_gather_bytes_saved` gauge."""
        return sum(int(s.size // self.num_blocks) * s.dtype.itemsize
                   for s in self._stores.values())

    def gather(self, blocks: list[int]) -> Any:
        """Chain → a batch-1, length-``len(blocks)·block_size`` cache
        tree (leaf paths identical to `init_cache`'s, non-K/V leaves
        zeroed) ready for `_prefill_suffix`'s prefix splice."""
        n = len(blocks)
        if n < 1:
            raise ValueError("empty block chain")
        total = n * self.block_size
        template = self._tree_templates.get(total)
        if template is None:
            template = jax.eval_shape(
                lambda: init_cache(self.model, 1, total))
            self._tree_templates[total] = template
        bids = jnp.asarray(blocks, jnp.int32)
        parts = {key: _gather_blocks(store, bids, n,
                                     stacked=self._stacked)
                 for key, store in self._stores.items()}

        def fill(path, leaf):
            if _is_kv(path):
                return parts[jax.tree_util.keystr(path)]
            return jnp.zeros(leaf.shape, leaf.dtype)
        return jax.tree_util.tree_map_with_path(fill, template)
