"""Dataset staging through the replicated store.

The reference distributes its dataset over SDFS before inferring: `put` the
images, workers `get` them to local disk, then the model reads local files
(`README.md:37-38`, `mp4_machinelearning.py:886-945`). This module is that
flow made native: a dataset is published ONCE into the replicated store as
packed uint8 shards + a JSON meta object, and every worker stages the
shards it needs on demand into a host-local cache (fetch once per shard per
host — re-replication keeps shards alive through failures like any other
store object).

Engine integration: pass ``dataset_root="store://<name>"`` anywhere a
dataset root is accepted (`InferenceEngine.infer`, the `inference` control/
shell verbs carry it through jobs) and workers resolve ranges against the
published dataset instead of local files.

Shards are raw uint8 bytes (no per-image codec) so staging is a straight
memcpy into the [N, S, S, 3] batch the device path consumes — decode cost
was paid once at publish time, not per query (the reference re-decodes
every image on every task, `alexnet_resnet.py:46-66`).
"""
from __future__ import annotations

import json
import os
import threading

import numpy as np

from idunno_tpu.engine.data import image_name, synthetic_image

STORE_SCHEME = "store://"


def dataset_meta_name(name: str) -> str:
    return f"dataset/{name}/meta"


def dataset_shard_name(name: str, k: int) -> str:
    return f"dataset/{name}/shard_{k}"


def publish_images(store, name: str, images: np.ndarray, *,
                   shard_size: int = 256) -> dict:
    """Publish a packed uint8 image block [N, S, S, 3] as store shards;
    returns the meta dict (incl. n/size/shard count)."""
    images = np.ascontiguousarray(images, dtype=np.uint8)
    if images.ndim != 4 or images.shape[1] != images.shape[2] \
            or images.shape[3] != 3:
        raise ValueError(f"want [N, S, S, 3] uint8, got {images.shape}")
    if shard_size < 1:
        raise ValueError(f"shard_size={shard_size}: must be >= 1")
    n, size = images.shape[0], images.shape[1]
    n_shards = -(-n // shard_size) if n else 0
    for k in range(n_shards):
        block = images[k * shard_size:(k + 1) * shard_size]
        store.put_bytes(dataset_shard_name(name, k), block.tobytes())
    meta = {"n": n, "size": size, "shard_size": shard_size,
            "n_shards": n_shards}
    store.put_bytes(dataset_meta_name(name), json.dumps(meta).encode())
    return meta


class StoreDataset:
    """Range reader over a published dataset with a host-local shard cache.

    ``cache_dir`` (one per host) holds fetched shards as flat files; every
    node fetches a shard at most once, matching the reference's
    stage-to-local-disk procedure. Thread-safe: worker job threads may
    load overlapping ranges concurrently."""

    def __init__(self, store, name: str,
                 cache_dir: str | None = None) -> None:
        self.store = store
        self.name = name
        blob, self.version = store.get_bytes(dataset_meta_name(name))
        meta = json.loads(blob)
        self.n = int(meta["n"])
        self.size = int(meta["size"])
        self.shard_size = int(meta["shard_size"])
        self.cache_dir = cache_dir
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
        self._mem: dict[int, np.ndarray] = {}
        self._lock = threading.Lock()

    def _shard_path(self, k: int) -> str:
        # version-qualified: a re-published dataset never serves stale cache
        return os.path.join(self.cache_dir, f"shard_{k}.v{self.version}.u8")

    # without a disk cache, shards live in RAM — bound how many (a full
    # dataset pinned in host memory per engine can OOM the node)
    _MEM_SHARDS_MAX = 64

    def _shard(self, k: int) -> np.ndarray:
        with self._lock:
            arr = self._mem.get(k)
        if arr is not None:
            return arr
        rows = min(self.shard_size, self.n - k * self.shard_size)
        shape = (rows, self.size, self.size, 3)
        nbytes = int(np.prod(shape))
        path = self._shard_path(k) if self.cache_dir else None
        if path is not None:
            if not (os.path.exists(path)
                    and os.path.getsize(path) == nbytes):  # torn write
                blob, _ = self.store.get_bytes(
                    dataset_shard_name(self.name, k))
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)                 # atomic vs readers
            # memmap: the OS page cache backs reads, nothing is pinned in
            # this process — host RSS stays bounded however big the dataset
            arr = np.memmap(path, dtype=np.uint8, mode="r", shape=shape)
        else:
            blob, _ = self.store.get_bytes(dataset_shard_name(self.name, k))
            arr = np.frombuffer(blob, dtype=np.uint8).reshape(shape)
        with self._lock:
            self._mem[k] = arr
            # bound the cache either way: RAM for frombuffer shards, open
            # file handles for memmaps (both re-acquire cheaply)
            while len(self._mem) > self._MEM_SHARDS_MAX:   # oldest-first
                self._mem.pop(next(iter(self._mem)))
        return arr

    def load_range(self, start: int,
                   end: int) -> tuple[list[str], np.ndarray]:
        """Indices [start, end] inclusive → (names, uint8 [N, S, S, 3]).
        Out-of-range indices get the deterministic synthetic placeholder —
        same contract as the local-file loader (result counts stay exact)."""
        indices = list(range(start, end + 1))
        names = [image_name(i) for i in indices]
        if not indices:
            return names, np.zeros((0, self.size, self.size, 3), np.uint8)
        out = np.empty((len(indices), self.size, self.size, 3), np.uint8)
        i = 0
        while i < len(indices):
            idx = indices[i]
            if not 0 <= idx < self.n:
                out[i] = synthetic_image(idx, self.size)
                i += 1
                continue
            k = idx // self.shard_size
            shard = self._shard(k)
            lo = idx - k * self.shard_size
            take = min(len(shard) - lo, len(indices) - i)
            out[i:i + take] = shard[lo:lo + take]
            i += take
        return names, out
