from idunno_tpu.engine.inference import InferenceEngine, QueryResult  # noqa: F401
