"""Seeded chaos harness: deterministic fault schedules over a full
in-process cluster, with global safety invariants checked continuously.

FoundationDB-style simulation testing (Zhou et al., SIGMOD 2021): one
``random.Random(seed)`` drives BOTH the fault schedule (partitions,
one-way cuts, probabilistic drop/dup/delay, kills) and the workload (CNN
queries, managed LM submits, SDFS puts), so any failing schedule replays
exactly from its seed. The reference has nothing like this — its failover
was only ever exercised by hand-killing VMs (SURVEY.md §4), and its
fencing-free design (`mp4_machinelearning.py:956-963`) cannot pass these
invariants at all.

Invariants (``ChaosCluster.check_invariants`` after ``converge``):
- at most one acting master per epoch, ever (fence owners are recorded at
  every step; two owners for one epoch number = split brain);
- at most one owner per POOL-SCOPE epoch (ISSUE 14: per-pool fences are
  sampled from every host's scope registry exactly like the cluster
  fence — two owners for one (scope, epoch) = per-pool split brain);
- at most one ownership CLAIMANT per (scope, claim seq) across every
  host's gossiped map, the first claim on each scope lands exactly on
  its rendezvous placement, and with a second pool the owners spread
  over more than one host (ISSUE 15: multi-owner control plane);
- zero stale-epoch messages ACCEPTED anywhere (a transport-level probe
  snapshots each receiver's fence before the handler runs: a stamped
  payload below that high-water mark must produce an ERROR, never an
  ACK) — and the same for scoped stamps below a scope's high-water;
- every CNN query acked by the surviving master lineage completes exactly
  once — result set exact, no duplicate records;
- every LM request admitted into the surviving journal reaches exactly one
  terminal state, no completion is delivered twice, and every completion
  surfaces from the POOL it was submitted to (cross-pool isolation: a
  deposed pool-A owner must never leak or lose pool-B work);
- every SDFS put acked by the surviving lineage reads back exactly, and
  each surviving version keeps >= min(replication_factor, holders-at-ack)
  alive holders (ring re-replication restored what a death took);
- membership views converge after heal.

The LM node tier is a deterministic stand-in (`ChaosControl`): tokens are
a pure function of (prompt, seed), so replay token-exactness is checkable
without a model — the real tier's epoch fencing and lm_submit idempotency
semantics are mirrored verb-for-verb from `serve/control.py`.
"""
from __future__ import annotations

import hashlib
import json
import os
import random
import time
from types import SimpleNamespace

from idunno_tpu.comm.inproc import InProcNetwork
from idunno_tpu.comm.message import Message
from idunno_tpu.comm.retry import call_with_retry
from idunno_tpu.comm.transport import TransportError
from idunno_tpu.config import ClusterConfig
from idunno_tpu.membership.epoch import (check_payload, check_scoped,
                                         observe_payload, place_scope,
                                         pool_scope)
from idunno_tpu.membership.service import MembershipService
from idunno_tpu.scheduler.fair import FairScheduler
# the typed owner-hop relay is SHARED with the real control plane (ISSUE
# 16): one class, so the sim and the product can never drift on which
# markers survive a forwarded hop
from idunno_tpu.serve.control import RelayedError as _RelayedError
from idunno_tpu.serve.failover import FailoverManager
from idunno_tpu.serve.inference_service import (InferenceService,
                                                InferenceServiceError)
from idunno_tpu.serve.lm_manager import LMPoolManager
from idunno_tpu.serve.metrics import MetricsTracker
from idunno_tpu.store.sdfs import FileStoreService, StoreError
from idunno_tpu.utils.spans import SpanStore, trace_from_payload
from idunno_tpu.utils.types import MessageType

# services whose handlers are epoch-fenced; the membership service is
# deliberately NOT probed — its gossip must accept any epoch stamp (that
# is how a deposed coordinator learns it was deposed)
PROBED_SERVICES = ("inference", "control", "store", "metadata")


class ChaosClock:
    """Fake wall clock shared by every node (tests/test_membership.py
    idiom) so suspicion timeouts are schedule-driven, not real-time."""

    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class ChaosEngine:
    """Deterministic CNN engine stand-in: 10 ms/image, name-derived
    records (same contract as the serving tests' FakeEngine)."""

    def __init__(self, host: str, clock: ChaosClock) -> None:
        self.host = host
        self.clock = clock

    def infer(self, name, start, end, dataset_root=None):
        n = end - start + 1
        self.clock.advance(0.01 * n)
        return SimpleNamespace(
            records=[(f"test_{i}.JPEG", f"class_{(i * 7) % 1000}", 0.9)
                     for i in range(start, end + 1)],
            elapsed_s=0.01 * n,
            weights="pretrained")


def lm_tokens(prompt: list[int], seed: int, max_new: int) -> list[int]:
    """The fake decode function: pure in (prompt, seed), so a journaled
    replay with the pinned seed is token-exact by construction."""
    base = sum(prompt) % 50257
    return list(prompt) + [(seed * 1000003 + i * 7919 + base) % 50257
                           for i in range(max_new)]


def chunk_content(chunk: list[int]) -> dict:
    """Deterministic stand-in for one KV block's leaf arrays: a pure
    function of the chunk tokens, so two replicas publishing the same
    prefix produce byte-identical blobs (the content-address dedupe
    property the real pool gets from causal attention) and every fetched
    blob is CHECKABLE against its tokens — a mismatch is a wrong-token
    graft, recorded as an invariant violation (ISSUE 17)."""
    import numpy as np
    return {"kv": np.asarray([(t * 31 + 7) % 997 for t in chunk],
                             np.int64)}




class ChaosControl:
    """Per-host control-verb handler: cluster routing + a fake node-local
    LM tier, mirroring `serve/control.py` (epoch fence at the top,
    owner-aware routing of pool verbs with a one-hop forward, the
    deposed-holder ``scope_owner`` redirect, per-name lm_submit
    idempotency purged on rebuild/stop)."""

    _POOL_VERBS = ("lm_submit", "lm_poll", "lm_stats", "lm_qos",
                   "lm_autoscale", "prefix_publish", "prefix_probe",
                   "prefix_fetch", "kv_handoff")

    def __init__(self, host: str, membership: MembershipService,
                 lm_manager: LMPoolManager, store=None,
                 violations: list | None = None) -> None:
        self.host = host
        self.membership = membership
        self.mgr = lm_manager
        # this host's FileStoreService + the cluster violation ledger:
        # the fake LM tier runs the REAL ClusterPrefixCache against the
        # real SDFS ring (ISSUE 17), checking fetched content inline
        self.store = store
        self.violations = violations if violations is not None else []
        self._loops: dict = {}     # name -> {"next", "done"}
        self._lm_idem: dict = {}   # (name, key) -> node-local row id

    def handle(self, service: str, msg: Message) -> Message:
        stale = check_payload(self.membership.epoch, msg.payload, self.host)
        if stale is not None:
            return stale
        # per-pool fence mirror (serve/control.py): a verb stamped by a
        # deposed POOL owner is rejected for that scope only
        stale = check_scoped(self.membership.scopes, msg.payload, self.host)
        if stale is not None:
            return stale
        try:
            out = self._dispatch(msg.payload.get("verb", ""), msg.payload)
            return Message(MessageType.ACK, self.host, out)
        except _RelayedError as e:
            return Message(MessageType.ERROR, self.host, e.payload)
        except Exception as e:  # noqa: BLE001 - RPC boundary
            return Message(MessageType.ERROR, self.host,
                           {"error": f"{type(e).__name__}: {e}"})

    def _forward_owner(self, p: dict, name: str) -> dict | None:
        """Owner-aware one-hop forward, mirroring
        serve/control.py:_forward_scope_owner — the claimed owner is
        trusted ahead of the local liveness view, ``_owner_hop`` guards
        against loops, and ERROR replies relay verbatim. None = no
        forwardable owner, fall through to the node-local tier."""
        if p.get("_owner_hop"):
            return None
        scope = pool_scope(name)
        owner = self.membership.owners.owner(scope)
        if owner is None or owner == self.host:
            alive = set(self.membership.members.alive_hosts())
            # quarantine-blind on purpose: this guess must match the
            # adoption formula (failover._adopt_scopes_of), not the
            # assignment-time view — see the note there
            owner = place_scope(scope, self.membership.config.hosts, alive)
        if owner is None or owner == self.host:
            return None
        self.mgr.service.metrics.record_counter("scope_owner_redirects")
        fwd = dict(p, _owner_hop=True,
                   epoch=list(self.membership.epoch.view()))
        try:
            out = self.mgr.transport.call(
                owner, "control",
                Message(MessageType.INFERENCE, self.host, fwd),
                timeout=0.5)
        except TransportError as e:
            raise ValueError(f"scope owner {owner} for {name!r} "
                             f"unreachable: {e}") from e
        if out is None:
            raise ValueError(
                f"scope owner {owner} for {name!r} gave no reply")
        observe_payload(self.membership.epoch, out.payload)
        if out.type is MessageType.ERROR:
            raise _RelayedError(dict(out.payload or {}))
        return dict(out.payload or {})

    def _dispatch(self, verb: str, p: dict) -> dict:
        mgr = self.mgr
        if mgr is not None and not p.get("local"):
            if verb == "lm_serve" and p.get("placement") == "assign":
                # owner landing of a master handoff (pool_assign): serve
                # here, no re-forward — a replay absorbs as already=True
                return mgr.serve(p, assigned=True)
            if p.get("placement") == "auto" and verb == "lm_serve":
                if not self.membership.is_acting_master:
                    raise ValueError("placement=auto must go to the "
                                     "acting master")
                return mgr.serve(p)
            name = p.get("name")
            if verb in self._POOL_VERBS and not mgr.has_pool(name):
                out = self._forward_owner(p, name)
                if out is not None:
                    return out
            if verb in self._POOL_VERBS and mgr.has_pool(name):
                claimed = self.membership.owners.owner(pool_scope(name))
                if claimed is None:
                    # unclaimed scope: the pre-ownership rule — only the
                    # acting master serves a managed journal
                    if not self.membership.is_acting_master:
                        raise ValueError(f"{self.host} is not the acting "
                                         f"master; journal fenced")
                elif claimed != self.host:
                    # deposed holder: adopter out-claimed this scope —
                    # step down for it only, typed redirect to the owner
                    mgr.step_down_scope(pool_scope(name))
                    self.mgr.service.metrics.record_counter(
                        "scope_owner_redirects")
                    raise _RelayedError({
                        "error": f"scope {pool_scope(name)} is owned by "
                                 f"{claimed}; redirect",
                        "scope": pool_scope(name),
                        "scope_owner": claimed})
                if verb == "lm_submit":
                    rid = mgr.submit(
                        name, [int(t) for t in p["prompt"]],
                        int(p["max_new"]),
                        seed=(int(p["seed"])
                              if p.get("seed") is not None else None),
                        tenant=str(p.get("tenant", "default")),
                        idem_key=p.get("idem"),
                        trace=trace_from_payload(p))
                    return {"id": rid}
                if verb == "lm_poll":
                    return mgr.poll(name)
                if verb == "lm_qos":
                    return mgr.qos(name)
                if verb == "lm_autoscale":
                    if p.get("policy"):
                        return mgr.autoscale_set(name, dict(p["policy"]))
                    return mgr.autoscale_get(name)
                if verb in ("prefix_publish", "prefix_probe",
                            "prefix_fetch"):
                    # same relay as serve/control.py:_route_cluster —
                    # prefix state lives on the serving node
                    return mgr.prefix_op(verb, name, p)
                if verb == "kv_handoff":
                    # same relay as serve/control.py:_route_cluster —
                    # block state lives on the serving replicas
                    return mgr.kv_handoff(name, p)
                return {"stats": mgr.stats(name)}
        # -- node-local fake LM tier --
        if verb == "lm_serve":
            name = p["name"]
            if name in self._loops and not p.get("reload"):
                return {"already": True}
            # prefill_chunk rides the spec (journaled, replayed on
            # failover like every serving knob): the fake tier models a
            # chunked admission as completion deferred by one poll round
            # — the watchdog/poll-retry machinery must tolerate a pool
            # that holds work across a poll without losing or duping it
            # n_model rides the spec the same way: the fake tier only
            # records the TP shape (no real mesh), proving the knob
            # journals/replays through failover like every serving knob
            self._loops[name] = {"next": 0, "done": [], "defer": [],
                                 "chunk": int(p.get("prefill_chunk")
                                              or 0),
                                 "n_model": int(p.get("n_model") or 1),
                                 "cp": None,
                                 "bs": int(p.get("kv_block_size") or 0),
                                 "tree": set(),
                                 "remote_hits": 0, "published": 0,
                                 "warmed": 0,
                                 # DistServe handoff gauges (ISSUE 18)
                                 "shipped": 0, "adopted": 0,
                                 "fallbacks": 0}
            if p.get("cluster_prefix") and self.store is not None:
                # ISSUE 17: the fake tier runs the REAL
                # ClusterPrefixCache against the real SDFS ring; only
                # the KV content is a stand-in (chunk_content). The
                # namespace is the pool BASE name so group replicas and
                # a failover rebuild share the published set.
                from idunno_tpu.serve.cluster_prefix import \
                    ClusterPrefixCache
                bs = int(p.get("kv_block_size") or 2)
                loop = self._loops[name]
                loop["bs"] = bs
                loop["cp"] = ClusterPrefixCache(
                    self.store, str(name).split("@", 1)[0], bs,
                    publish_min_hits=0)
            for k in [k for k in self._lm_idem if k[0] == name]:
                del self._lm_idem[k]
            return {"slots": int(p.get("slots", 4))}
        if verb == "lm_submit":
            name = p["name"]
            if name not in self._loops:
                raise ValueError(f"no lm_serve pool for {name!r}; "
                                 "call lm_serve first")
            key = p.get("idem")
            if key is not None and (name, key) in self._lm_idem:
                return {"id": self._lm_idem[(name, key)],
                        "duplicate": True}
            loop = self._loops[name]
            rid = loop["next"]
            loop["next"] += 1
            prompt = [int(t) for t in p["prompt"]]
            if loop["cp"] is not None:
                self._prefix_admit(name, loop, prompt,
                                   str(p.get("tenant", "default")))
            toks = lm_tokens(prompt, int(p.get("seed") or 0),
                             int(p["max_new"]))
            comp = {"id": rid, "tokens": toks,
                    "prompt_len": len(prompt), "service_s": 0.001}
            # chunked pools admit over multiple steps: completion lands
            # a poll round later (tokens identical — chunking is pure
            # scheduling, the exactness ledger must not notice)
            dest = "defer" if loop["chunk"] and \
                len(prompt) > loop["chunk"] else "done"
            loop[dest].append(comp)
            if key is not None:
                self._lm_idem[(name, key)] = rid
            return {"id": rid}
        if verb == "lm_poll":
            name = p["name"]
            if name not in self._loops:
                raise ValueError(f"no lm_serve pool for {name!r}; "
                                 "call lm_serve first")
            loop = self._loops[name]
            done = loop["done"]
            loop["done"], loop["defer"] = loop["defer"], []
            return {"completions": done}
        if verb == "lm_stop":
            self._loops.pop(p["name"], None)
            for k in [k for k in self._lm_idem if k[0] == p["name"]]:
                del self._lm_idem[k]
            return {"stopped": True}
        if verb == "lm_qos":
            # the fake tier has no gateway; the autoscaler's live-gauge
            # reader treats a qos-less node as n=0 (never scales on it) —
            # chaos schedules drive pressure through the injected
            # gauges_fn instead
            return {"qos": None}
        if verb in ("prefix_publish", "prefix_probe", "prefix_fetch"):
            return self._prefix_verb(verb, p)
        if verb == "kv_handoff":
            return self._handoff_verb(p)
        raise ValueError(f"unknown control verb {verb!r}")

    # -- fake-tier cluster prefix cache (ISSUE 17) -------------------------

    @staticmethod
    def _chunks(toks: list[int], bs: int) -> list[tuple[int, ...]]:
        return [tuple(toks[j * bs:(j + 1) * bs])
                for j in range(len(toks) // bs)]

    @staticmethod
    def _tree_depth(tree: set, chunks: list, cap: int) -> int:
        d = 0
        while d < cap and tuple(chunks[:d + 1]) in tree:
            d += 1
        return d

    def _fetch_into(self, name: str, loop: dict, toks: list[int],
                    local: int, depth: int) -> int:
        """Fetch depths [local, depth) through the REAL cache, verify
        each blob's content against the pure ``chunk_content`` of its
        chunk (a mismatch is a wrong-token graft — invariant violation),
        and insert contiguously into the fake radix tree. Returns blocks
        grafted. A store failure mid-fetch just ends the graft early —
        degradation is legal, corruption is not."""
        import numpy as np
        cp, bs, tree = loop["cp"], loop["bs"], loop["tree"]
        chunks = self._chunks(toks, bs)
        got = 0
        for i, (chunk, arrays) in enumerate(cp.fetch(toks, local, depth)):
            if tuple(chunk) != chunks[local + i]:
                self.violations.append(
                    f"{self.host}/{name}: fetched chunk at depth "
                    f"{local + i} mismatches the prompt (double-prefill "
                    f"hazard): {chunk}")
                return got
            want = chunk_content(list(chunk))["kv"]
            if not np.array_equal(np.asarray(arrays.get("kv")), want):
                self.violations.append(
                    f"{self.host}/{name}: wrong-token KV content fetched "
                    f"at depth {local + i} for chunk {chunk}")
                return got
            tree.add(tuple(chunks[:local + i + 1]))
            got += 1
        return got

    def _prefix_admit(self, name: str, loop: dict, prompt: list[int],
                      tenant: str = "default") -> None:
        """Model the admission-path flow over the REAL subsystem: local
        radix depth from the fake tree, ring probe + suffix-only fetch,
        inline content checks, then insert + publish. Mirrors
        engine/serve_lm.py:_admit/_finish_admission."""
        cp, bs, tree = loop["cp"], loop["bs"], loop["tree"]
        if len(prompt) <= bs:
            return
        # admission caps the hit so >= 1 token always prefills
        want = (len(prompt) - 1) // bs
        chunks = self._chunks(prompt, bs)
        local = self._tree_depth(tree, chunks, want)
        hit = local
        if local < want:
            depth = cp.probe(prompt[:want * bs], start_depth=local)
            if depth > local:
                got = self._fetch_into(name, loop, prompt[:want * bs],
                                       local, depth)
                if got:
                    hit = local + got
                    loop["remote_hits"] += 1
                    cp.remote_hits += 1
        if hit > want or len(prompt) - hit * bs < 1:
            self.violations.append(
                f"{self.host}/{name}: admission covered {hit} blocks of "
                f"a {len(prompt)}-token prompt (no tokens left to "
                f"prefill)")
            return
        for d in range(1, want + 1):
            tree.add(tuple(chunks[:d]))
        out = cp.publish(
            [t for c in chunks[:want] for t in c], want,
            lambda j: chunk_content(list(chunks[j])), tenant=tenant)
        loop["published"] += out["published"]

    def _prefix_verb(self, verb: str, p: dict) -> dict:
        """Node-local handlers mirroring serve/lm_pool.py:_fulfill_prefix
        over the fake tier's tree + the real ClusterPrefixCache."""
        name = p["name"]
        loop = self._loops.get(name)
        if loop is None:
            raise ValueError(f"no lm_serve pool for {name!r}; "
                             "call lm_serve first")
        cp, bs, tree = loop["cp"], loop["bs"], loop["tree"]
        if cp is None:
            raise ValueError(f"pool {name!r} has no cluster prefix "
                             "cache (serve with cluster_prefix=...)")
        if verb == "prefix_probe":
            toks = [int(t) for t in p.get("tokens") or []]
            chunks = self._chunks(toks, bs)
            local = self._tree_depth(tree, chunks, len(chunks))
            return {"local_blocks": local,
                    "remote_blocks": cp.probe(toks),
                    "namespace": cp.namespace, "block_size": bs}
        if verb == "prefix_publish":
            targets = []
            if p.get("tokens") is not None:
                targets.append([int(t) for t in p["tokens"]])
            else:
                # every maximal chain in the tree (no extension present)
                for path in sorted(tree):
                    if not any(len(o) > len(path) and
                               o[:len(path)] == path for o in tree):
                        targets.append([t for c in path for t in c])
            published = 0
            for toks in targets:
                chunks = self._chunks(toks, bs)
                out = cp.publish(
                    toks, len(chunks),
                    lambda j, c=chunks: chunk_content(list(c[j])),
                    force=True)
                published += out["published"]
            loop["published"] += published
            return {"published_blocks": published,
                    "chains": len(targets)}
        # prefix_fetch: warm explicit tokens or a tenant's published set
        targets = []
        if p.get("tokens") is not None:
            targets.append([int(t) for t in p["tokens"]])
        elif p.get("tenant") is not None:
            targets = [[int(t) for t in e.get("tokens", [])]
                       for e in cp.tenant_entries(str(p["tenant"]))]
        fetched = 0
        for toks in targets:
            chunks = self._chunks(toks, bs)
            local = self._tree_depth(tree, chunks, len(chunks))
            if local >= len(chunks):
                continue
            depth = cp.probe(toks, start_depth=local)
            if depth > local:
                fetched += self._fetch_into(name, loop, toks, local,
                                            depth)
        cp.warm_blocks += fetched
        loop["warmed"] += fetched
        return {"fetched_blocks": fetched, "targets": len(targets)}

    # -- fake-tier DistServe KV handoff (ISSUE 18) -------------------------

    def _handoff_verb(self, p: dict) -> dict:
        """Node-local handlers mirroring serve/control.py:_kv_handoff
        over the fake tier's radix tree, with the REAL KVC1 wire codec
        (store/kv_chain.py): a ship encodes the prefill replica's blocks,
        pushes them point-to-point to the decode node, and the adopt side
        decodes with ``expect_tokens`` + checks content against the pure
        ``chunk_content`` — a mismatch is a wrong-token graft, recorded
        as an invariant violation exactly like the prefix-cache path."""
        import numpy as np

        from idunno_tpu.store.kv_chain import decode_block, encode_block
        name = p["name"]
        loop = self._loops.get(name)
        if loop is None:
            raise ValueError(f"no lm_serve pool for {name!r}; "
                             "call lm_serve first")
        bs = int(loop.get("bs") or 0)
        if bs <= 0:
            raise ValueError(f"pool {name!r} has no KV block tier "
                             "(serve with kv_block_size > 0)")
        op = p.get("op")
        toks = [int(t) for t in p.get("tokens") or []]
        chunks = self._chunks(toks, bs)
        # admission cap mirror: >= 1 token must remain to prefill
        want = max(0, (len(toks) - 1) // bs)
        tree = loop["tree"]
        if op == "probe":
            return {"depth": self._tree_depth(tree, chunks, want),
                    "want": want, "block_size": bs}
        if op == "adopt":
            start = int(p.get("start_depth") or 0)
            wrote = 0
            nbytes = 0
            for j, blob_s in enumerate(p.get("blobs") or []):
                d = start + j
                blob = blob_s.encode("latin-1")
                nbytes += len(blob)
                _, arrays = decode_block(blob,
                                         expect_tokens=list(chunks[d]))
                wantkv = chunk_content(list(chunks[d]))["kv"]
                if not np.array_equal(np.asarray(arrays.get("kv")),
                                      wantkv):
                    self.violations.append(
                        f"{self.host}/{name}: wrong-token KV content "
                        f"adopted at depth {d} for chunk {chunks[d]} "
                        f"(handoff corruption)")
                    raise ValueError("handoff blob content mismatch")
                tree.add(tuple(chunks[:d + 1]))
                wrote += 1
            loop["adopted"] += wrote
            return {"adopted": wrote, "wrote": wrote,
                    "depth": start + wrote, "bytes": nbytes}
        if op == "ship":
            target_host = p["target_host"]
            target_name = p["target_name"]
            # model the prefill leg: this replica fills its own blocks
            for d in range(1, want + 1):
                tree.add(tuple(chunks[:d]))

            def rcall(fwd: dict) -> dict:
                out = self.mgr.transport.call(
                    target_host, "control",
                    Message(MessageType.INFERENCE, self.host,
                            dict(fwd, local=True,
                                 epoch=list(self.membership.epoch
                                            .view()))),
                    timeout=0.5)
                if out is None:
                    raise TransportError(
                        f"kv_handoff: {target_host} gave no reply",
                        reason="timeout")
                observe_payload(self.membership.epoch, out.payload)
                if out.type is MessageType.ERROR:
                    raise ValueError(
                        str((out.payload or {}).get("error", "")))
                return dict(out.payload or {})

            probe = rcall({"verb": "kv_handoff", "op": "probe",
                           "name": target_name, "tokens": toks})
            depth = int(probe.get("depth") or 0)
            if depth >= want:
                # delta-only ship: the decode replica already holds the
                # full chain (a replayed ship after a lost ACK)
                return {"shipped": 0, "bytes": 0, "depth": depth,
                        "already": True}
            blobs = []
            for d in range(depth, want):
                blob = encode_block(
                    {"tokens": list(chunks[d]), "depth": d,
                     "block_size": bs},
                    chunk_content(list(chunks[d])))
                blobs.append(blob.decode("latin-1"))
            out = rcall({"verb": "kv_handoff", "op": "adopt",
                         "name": target_name, "tokens": toks,
                         "blobs": blobs, "start_depth": depth})
            loop["shipped"] += int(out.get("wrote") or 0)
            return {"shipped": int(out.get("wrote") or 0),
                    "bytes": int(out.get("bytes") or 0),
                    "depth": int(out.get("depth") or 0)}
        if op == "fallback":
            loop["fallbacks"] += 1
            return {"fallback": True}
        raise ValueError(f"unknown kv_handoff op {op!r}")


class ChaosCluster:
    """A 5-host in-process cluster (coordinator n0, standby n1) with every
    control-plane layer wired the way `serve/node.py` wires it, a seeded
    fault/workload schedule, and invariant recording."""

    LM_POOL = "chaos-lm"
    LM_POOL_B = "chaos-lmB"
    LM_GROUP = "chaos-grp"
    LM_GROUP_D = "chaos-dsg"

    def __init__(self, seed: int, data_dir: str, n_hosts: int = 5,
                 prefill_chunk: int = 0, n_model: int = 1,
                 autoscale: bool = False, multi_pool: bool = False,
                 cluster_prefix: bool = False,
                 distserve: bool = False,
                 fail_slow: bool = False) -> None:
        self.seed = seed
        self.prefill_chunk = prefill_chunk
        self.n_model = n_model
        # gate ALL group workload behind the flag: the group ops draw
        # extra rng, which would shift every existing seed's schedule
        self.autoscale = autoscale
        # ISSUE 14: a second concurrent managed pool, flag-gated for the
        # same reason — its submissions draw extra rng in step()
        self.multi_pool = multi_pool
        # ISSUE 17: cluster prefix cache over the SDFS ring — flag-gated
        # for the same reason (prefix submissions draw extra rng, and the
        # real store traffic the cache generates draws chaos rng)
        self.cluster_prefix = cluster_prefix
        # ISSUE 18: a role-split replica group (prefill + decode) with a
        # KV block pool, so long-prompt submissions route in DistServe
        # handoff mode (manager ships real KVC1 blobs between the fake
        # loops) — flag-gated: submissions AND ship RPCs draw chaos rng
        self.distserve = distserve
        # ISSUE 20: gray-failure schedule — flag-gated because attaching
        # the health ledger to the transports, the victim rng draw, and
        # the per-step latency-sampling sweep all consume rng / send
        # extra datagrams, which would shift every existing seed
        self.fail_slow = fail_slow
        self.slow_victim: str | None = None
        self.slow_prober: str | None = None
        self.saw_quarantine = False
        # per-host consecutive steps the victim was missing from that
        # host's alive view while both ends' links were clean
        self._leave_streak: dict[str, int] = {}
        # created before the host loop: the controls hold a reference so
        # the fake tier's inline content checks (wrong-token graft,
        # double-prefill) land in the same invariant ledger
        self.violations: list[str] = []
        # synthetic interactive-p95 the injected gauges_fn reports for
        # group replicas; schedules script overload/underload through it
        self.group_pressure = 0.0
        self._steps_run = 0
        # overload for the first chunk of a seeded schedule, then idle:
        # one run crosses the scale-out threshold AND the scale-in one
        self.overload_steps = 24
        self.rng = random.Random(seed)
        self.cfg = ClusterConfig(
            hosts=tuple(f"n{i}" for i in range(n_hosts)),
            coordinator="n0", standby_coordinator="n1", introducer="n0",
            query_batch_size=100, query_interval_s=0.0,
            straggler_timeout_s=4.0, rpc_retry_deadline_s=0.5)
        self.net = InProcNetwork(seed=seed)
        self.clock = ChaosClock()
        if fail_slow:
            # victim off the coordinator chain (the limp is a worker-side
            # gray failure; deposing masters is the kill schedules' job);
            # the prober is a fixed second host whose ledger derives the
            # verdict and gossips it
            self.slow_victim = self.rng.choice(self.cfg.hosts[2:])
            self.slow_prober = ("n3" if self.slow_victim == "n2"
                                else "n2")
        self.members: dict[str, MembershipService] = {}
        self.services: dict[str, InferenceService] = {}
        self.stores: dict[str, FileStoreService] = {}
        self.failovers: dict[str, FailoverManager] = {}
        self.managers: dict[str, LMPoolManager] = {}
        self.controls: dict[str, ChaosControl] = {}
        # per-host span stores on the FAKE clock: span capture runs through
        # every chaos schedule (the whole point — traces of the runs that
        # trip invariants), and fake-clock timestamps make replays of one
        # seed produce identical waterfalls
        self.spans: dict[str, SpanStore] = {}
        # populated by check_invariants on any invariant trip: the last
        # window of every host's spans, so the failing request's trace is
        # in hand without re-running the schedule
        self.last_span_dump: dict[str, list[dict]] | None = None
        for h in self.cfg.hosts:
            t = self.net.transport(h)
            self.spans[h] = SpanStore(h, clock=self.clock)
            self.members[h] = MembershipService(h, self.cfg, t,
                                                clock=self.clock)
            if fail_slow:
                # node.py wiring, flag-gated: every reliable call now
                # feeds the caller's ledger with the net's SYNTHESIZED
                # latency (call_latency — no clock advance, no rng)
                t.health = self.members[h].health
            self.services[h] = InferenceService(
                h, self.cfg, t, self.members[h],
                ChaosEngine(h, self.clock),
                metrics=MetricsTracker(clock=self.clock),
                scheduler=FairScheduler(self.cfg,
                                        rng=random.Random(seed),
                                        clock=self.clock),
                clock=self.clock)
            self.services[h].spans = self.spans[h]
            self.stores[h] = FileStoreService(
                h, self.cfg, t, self.members[h],
                os.path.join(data_dir, h))
            self.stores[h].spans = self.spans[h]
            mgr = LMPoolManager(h, self.cfg, t, self.members[h],
                                inference_service=self.services[h])
            mgr.spans = self.spans[h]
            # the fake tier completes instantly: shrink the watchdog so a
            # poll reply lost to chaos re-forwards within the convergence
            # loop instead of after the production 120 s allowance
            mgr.request_timeout_s = 0.2
            mgr.build_rpc_timeout_s = 0.5
            self.managers[h] = mgr
            self.failovers[h] = FailoverManager(
                h, self.cfg, t, self.members[h], self.services[h],
                lm_manager=mgr)
            self.services[h].wal_hook = self.failovers[h].wal_append
            # node.py wiring: scaling decisions write ahead to the standby
            mgr.failover = self.failovers[h]
            # the autoscaler runs on the fake clock (dwell/drain windows
            # are schedule-driven) and, when the group workload is on,
            # reads scripted pressure instead of live gateway RPCs
            mgr.autoscaler.clock = self.clock
            if autoscale:
                mgr.autoscaler.gauges_fn = (
                    lambda name, _m=mgr: self._scripted_gauges(_m, name))
            self.controls[h] = ChaosControl(
                h, self.members[h], mgr, store=self.stores[h],
                violations=self.violations)
            t.serve("control", self.controls[h].handle)
        # invariant recorders (violations created above, pre-host-loop)
        self.epoch_owners: dict[int, set[str]] = {}
        self.acting_by_epoch: dict[int, set[str]] = {}
        # (scope, epoch) -> owners seen: >1 owner = per-pool split brain
        self.scope_owners: dict[tuple[str, int], set[str]] = {}
        # (scope, claim seq) -> claimants seen across every host's
        # gossiped ownership map: >1 = conflicting placement claims
        self.claim_owners: dict[tuple[str, int], set[str]] = {}
        # scope -> the deterministic rendezvous owner over the FULL host
        # set (filled after the pools are served): the seq-1 claim must
        # land exactly there on every run of this seed
        self.expected_owners: dict[str, str] = {}
        self._wrap_probes()
        # workload ledgers
        self._serial = 0
        self.cnn_acked: list[tuple[str, int, int, int]] = []  # model,q,lo,hi
        self.lm_acked: list[dict] = []       # {serial, prompt, seed, max_new}
        self.lmb_acked: list[dict] = []      # second-pool submissions
        # every ATTEMPTED lm submit, acked or not: a submit whose ACK was
        # lost may still have been journaled (the classic "maybe" outcome)
        # and legitimately completes — but tokens from a request nobody
        # ever attempted would mean cross-wired journals
        self.lm_attempted: list[dict] = []
        self.grp_acked: list[dict] = []      # group-routed lm submissions
        self.lmp_acked: list[dict] = []      # shared-head prefix workload
        self.lmh_acked: list[dict] = []      # distserve handoff workload
        # (name, version, blob, holders-at-ack): the holder set feeds the
        # ring-RF invariant — a death must not shrink it below min(RF, |set|)
        self.sdfs_acked: list[tuple[str, int, bytes, frozenset]] = []
        self.lm_delivered: dict[tuple, int] = {}   # token tuple -> count
        # token tuple -> pool name that delivered it (cross-pool isolation:
        # must equal the pool the tokens were submitted to)
        self.lm_delivered_pool: dict[tuple, str] = {}
        for h in self.cfg.hosts:
            self.members[h].join()
            self.clock.advance(0.01)
        self.pump_membership(waves=3)
        # one managed decode pool up-front; its journal rides failover
        out = self._client_control("n2", {
            "verb": "lm_serve", "placement": "auto", "name": self.LM_POOL,
            "prompt_len": 8, "max_len": 64, "slots": 4,
            **({"prefill_chunk": self.prefill_chunk}
               if self.prefill_chunk else {}),
            **({"n_model": self.n_model}
               if self.n_model > 1 else {}),
            **({"cluster_prefix": True, "kv_block_size": 2}
               if self.cluster_prefix else {})})
        assert out.get("node") or out.get("already"), out
        if multi_pool:
            # a SECOND independent managed pool: its journal, fence scope,
            # and WAL segment must ride failover without ever coupling to
            # the first pool's (cross-pool isolation invariant)
            outb = self._client_control("n3", {
                "verb": "lm_serve", "placement": "auto",
                "name": self.LM_POOL_B, "prompt_len": 8, "max_len": 64,
                "slots": 4})
            assert outb.get("node") or outb.get("already"), outb
        if autoscale:
            # a replica group under a tight policy: windows sized to the
            # 0.3 s pump waves so one schedule crosses both thresholds
            gout = self._client_control("n2", {
                "verb": "lm_serve", "placement": "auto",
                "name": self.LM_GROUP, "prompt_len": 8, "max_len": 64,
                "slots": 4,
                "autoscale": {"deadline_slack_s": 1.0,
                              "scale_in_frac": 0.25,
                              "dwell_s": 1.0, "drain_window_s": 1.0,
                              "max_replicas": 3}})
            assert gout.get("group") or gout.get("already"), gout
        if distserve:
            # a role-split group with a KV block pool: prefill-heavy
            # prompts (>= 4 tokens) route in handoff mode. The policy is
            # DISABLED so the autoscaler never retires the role pair
            # mid-schedule — the handoff path itself is what is under
            # test, not scaling.
            dout = self._client_control("n2", {
                "verb": "lm_serve", "placement": "auto",
                "name": self.LM_GROUP_D, "prompt_len": 8, "max_len": 64,
                "slots": 4, "kv_block_size": 2,
                "autoscale": {"enabled": False,
                              "prefill_len_threshold": 4,
                              "max_replicas": 3}})
            assert dout.get("group") or dout.get("already"), dout
            owner = next(h for h in self.cfg.hosts
                         if self.LM_GROUP_D in self.managers[h]._groups)
            sd = self.managers[owner].group_spawn(self.LM_GROUP_D,
                                                  role="prefill")
            assert sd is not None, "distserve prefill spawn failed"
        names = ([self.LM_POOL]
                 + ([self.LM_POOL_B] if multi_pool else [])
                 + ([self.LM_GROUP] if autoscale else [])
                 + ([self.LM_GROUP_D] if distserve else []))
        full = set(self.cfg.hosts)
        self.expected_owners = {
            pool_scope(n): place_scope(pool_scope(n), self.cfg.hosts, full)
            for n in names}

    # -- probes -----------------------------------------------------------

    def _wrap_probes(self) -> None:
        for h in self.cfg.hosts:
            t = self.net._nodes[h]
            fence = self.members[h].epoch
            scopes = self.members[h].scopes
            for svc in PROBED_SERVICES:
                handler = t._handlers.get(svc)
                if handler is None:
                    continue
                t._handlers[svc] = self._probe(h, svc, fence, scopes,
                                               handler)

    def _probe(self, host, svc, fence, scopes, handler):
        def wrapped(service, msg):
            pre = fence.current()     # BEFORE the handler can observe
            sp = (msg.payload or {}).get("scope_epoch")
            pre_scope = (scopes.fence(str(sp[0])).current()
                         if sp else None)
            out = handler(service, msg)
            ep = (msg.payload or {}).get("epoch")
            if (ep and int(ep[0]) < pre and out is not None
                    and out.type is not MessageType.ERROR):
                self.violations.append(
                    f"{host}/{svc} ACKed stale epoch {ep[0]} < {pre}")
            if (sp and int(sp[1]) < pre_scope and out is not None
                    and out.type is not MessageType.ERROR):
                self.violations.append(
                    f"{host}/{svc} ACKed stale scope {sp[0]} "
                    f"epoch {sp[1]} < {pre_scope}")
            return out
        return wrapped

    def record_fences(self) -> None:
        """Sample every node's fence view: two owners for one epoch — or
        two nodes acting as master under one epoch — is split brain.
        Scope fences are sampled the same way: two owners for one
        (scope, epoch) is per-pool split brain."""
        for h in self.cfg.hosts:
            e, owner = self.members[h].epoch.view()
            if owner is not None:
                self.epoch_owners.setdefault(e, set()).add(owner)
            if self.members[h].is_acting_master:
                self.acting_by_epoch.setdefault(
                    self.members[h].epoch.current(), set()).add(h)
            for scope, view in self.members[h].scopes.view_all().items():
                se, sowner = int(view[0]), view[1]
                if sowner is not None:
                    self.scope_owners.setdefault(
                        (scope, se), set()).add(sowner)
            # ownership claims (ISSUE 15): two hosts claiming one
            # (scope, seq) would mean the rendezvous/adoption protocol
            # minted conflicting owners — routing split brain
            for scope, view in self.members[h].owners.view_all().items():
                self.claim_owners.setdefault(
                    (scope, int(view[1])), set()).add(view[0])

    # -- client helpers (route like real clients: chain + retry) ----------

    def _client_control(self, client: str, payload: dict,
                        idem: str | None = None) -> dict:
        if idem is not None:
            payload = dict(payload, idem=idem)
        t = self.net._nodes[client]
        # owner-aware pre-route (ISSUE 15): pool-directed verbs go to the
        # client's gossiped scope-owner view FIRST — the chain stays as
        # fallback, and a typed scope_owner redirect adds ONE extra hop
        targets = []
        name = payload.get("name")
        if (payload.get("verb") in ChaosControl._POOL_VERBS
                and payload.get("placement") is None and name):
            o = self.members[client].owners.owner(pool_scope(name))
            if o is not None:
                targets.append(o)
        for x in (self.members[client].acting_master(),
                  self.cfg.coordinator, self.cfg.standby_coordinator):
            if x not in targets:
                targets.append(x)
        last = None
        redirected = False
        i = 0
        while i < len(targets):
            target = targets[i]
            i += 1
            try:
                out = call_with_retry(
                    lambda target=target: t.call(
                        target, "control",
                        Message(MessageType.INFERENCE, client, payload)),
                    attempts=2, base_s=0.0, cap_s=0.0, deadline_s=0.2,
                    sleep=lambda s: None)
            except TransportError as e:
                last = e
                continue
            if out is None:
                continue
            err = out.payload.get("error", "")
            if out.type is MessageType.ERROR:
                ro = out.payload.get("scope_owner")
                if ro and not redirected and ro not in targets:
                    # follow exactly one typed redirect to the claimed
                    # owner the deposed holder named
                    redirected = True
                    targets.insert(i, ro)
                    last = err
                    continue
                if ("acting master" in err or "fenced" in err
                        or "scope owner" in err or ro
                        or out.payload.get("stale_epoch")):
                    last = err
                    continue
                raise RuntimeError(err)
            return out.payload
        raise TransportError(f"no master reachable: {last}")

    # -- workload ops -----------------------------------------------------

    def op_cnn(self, client: str) -> None:
        self._serial += 1
        model = f"m{self._serial}"        # one model per logical query:
        lo = self._serial * 100           # ack/result matching is exact
        hi = lo + 19                      # even across deposed lineages
        try:
            q = self.services[client].submit_query(model, lo, hi)
        except (InferenceServiceError, TransportError, StoreError):
            return                        # no master reachable — lost, fine
        self.cnn_acked.append((model, q, lo, hi))

    def op_lm(self, client: str) -> None:
        self._serial += 1
        s = self._serial
        prompt = [s % 251, (s * 7) % 251, (s * 13) % 251]
        self.lm_attempted.append({"serial": s, "prompt": prompt,
                                  "seed": s, "max_new": 4,
                                  "pool": self.LM_POOL})
        try:
            out = self._client_control(
                client, {"verb": "lm_submit", "name": self.LM_POOL,
                         "prompt": prompt, "max_new": 4, "seed": s},
                idem=f"{client}:{s}")
        except (TransportError, RuntimeError):
            return
        self.lm_acked.append({"serial": s, "rid": int(out["id"]),
                              "prompt": prompt, "seed": s, "max_new": 4})

    def op_lm_b(self, client: str) -> None:
        """A submission to the SECOND managed pool (ISSUE 14). Prompts are
        serial-unique, so token keys stay globally unique and the global
        exactly-once ledger decomposes per pool; the delivered-pool
        attribution check is what makes cross-pool isolation explicit."""
        self._serial += 1
        s = self._serial
        prompt = [s % 251, (s * 7) % 251, (s * 13) % 251]
        self.lm_attempted.append({"serial": s, "prompt": prompt,
                                  "seed": s, "max_new": 4,
                                  "pool": self.LM_POOL_B})
        try:
            out = self._client_control(
                client, {"verb": "lm_submit", "name": self.LM_POOL_B,
                         "prompt": prompt, "max_new": 4, "seed": s},
                idem=f"{client}:{s}:b")
        except (TransportError, RuntimeError):
            return
        self.lmb_acked.append({"serial": s, "rid": int(out["id"]),
                               "prompt": prompt, "seed": s, "max_new": 4})

    def op_lm_group(self, client: str) -> None:
        """A group submission: routes like op_lm but lands on whichever
        replica the group picks; the seed is pinned by the client, so
        tokens are replica-independent and ride the same exactness
        ledger as pool submissions."""
        self._serial += 1
        s = self._serial
        prompt = [s % 251, (s * 7) % 251, (s * 13) % 251]
        self.lm_attempted.append({"serial": s, "prompt": prompt,
                                  "seed": s, "max_new": 4,
                                  "pool": self.LM_GROUP})
        try:
            out = self._client_control(
                client, {"verb": "lm_submit", "name": self.LM_GROUP,
                         "prompt": prompt, "max_new": 4, "seed": s,
                         "tenant": f"t{s % 3}"},
                idem=f"{client}:{s}:g")
        except (TransportError, RuntimeError):
            return
        self.grp_acked.append({"serial": s, "grid": int(out["id"]),
                               "prompt": prompt, "seed": s, "max_new": 4})

    # shared 6-token head = exactly 3 full blocks at kv_block_size=2:
    # every prefix submission publishes/remote-hits the SAME chain, so a
    # serving-node death followed by a failover rebuild must re-derive it
    # from the ring (the fake radix tree died with the node)
    PREFIX_HEAD = (11, 13, 17, 19, 23, 29)

    def op_lm_prefix(self, client: str) -> None:
        """A shared-head submission to the prefix-enabled pool (ISSUE
        17): the head is 3 publishable blocks, the 1-token tail keeps
        the token tuple serial-unique for the exactness ledger. The
        fake tier's admission probes/fetches/publishes the head through
        the REAL ClusterPrefixCache; content checks append violations."""
        self._serial += 1
        s = self._serial
        prompt = list(self.PREFIX_HEAD) + [s % 251]
        self.lm_attempted.append({"serial": s, "prompt": prompt,
                                  "seed": s, "max_new": 4,
                                  "pool": self.LM_POOL})
        try:
            out = self._client_control(
                client, {"verb": "lm_submit", "name": self.LM_POOL,
                         "prompt": prompt, "max_new": 4, "seed": s},
                idem=f"{client}:{s}:p")
        except (TransportError, RuntimeError):
            return
        self.lmp_acked.append({"serial": s, "rid": int(out["id"]),
                               "prompt": prompt, "seed": s, "max_new": 4})

    def op_lm_handoff(self, client: str) -> None:
        """A LONG-prompt submission to the role-split group (ISSUE 18):
        7 tokens crosses the prefill_len_threshold (4), so the manager
        routes it in handoff mode — the prefill replica fills + ships 3
        KV blocks to the tenant-sticky decode replica before the request
        forwards there. Tokens stay serial-unique, so the submission
        rides the same exactness ledger; a ship that dies mid-flight
        must fall back or replay, never lose or double the request."""
        self._serial += 1
        s = self._serial
        prompt = [s % 251, (s * 7) % 251, (s * 13) % 251,
                  (s * 17) % 251, (s * 19) % 251, (s * 23) % 251,
                  (s * 29) % 251]
        self.lm_attempted.append({"serial": s, "prompt": prompt,
                                  "seed": s, "max_new": 4,
                                  "pool": self.LM_GROUP_D})
        try:
            out = self._client_control(
                client, {"verb": "lm_submit", "name": self.LM_GROUP_D,
                         "prompt": prompt, "max_new": 4, "seed": s,
                         "tenant": f"t{s % 3}"},
                idem=f"{client}:{s}:h")
        except (TransportError, RuntimeError):
            return
        self.lmh_acked.append({"serial": s, "hrid": int(out["id"]),
                               "prompt": prompt, "seed": s, "max_new": 4})

    def probe_sweep(self, prober: str) -> None:
        """One latency-sampling sweep (fail_slow schedules only): the
        prober calls every peer once so its ledger holds >= min_samples
        on the whole fleet — the fleet median needs healthy samples, not
        just the victim's. Replies (even ERROR) observe the synthesized
        latency; a cut link observes an error sample. Consumes net rng,
        so it only ever runs under the fail_slow flag."""
        t = self.net._nodes[prober]
        for peer in self.cfg.hosts:
            if peer == prober:
                continue
            try:
                t.call(peer, "control",
                       Message(MessageType.INFERENCE, prober,
                               {"verb": "health_probe"}))
            except TransportError:
                pass

    def _scripted_gauges(self, mgr: LMPoolManager, name: str) -> dict:
        """Deterministic stand-in for `group_gauges`: scripted p95
        pressure (one number for the whole group), real journal backlog
        from the manager the autoscaler is ticking on."""
        out: dict = {}
        with mgr._lock:
            g = mgr._groups.get(name)
            if g is None:
                return out
            for r, meta in g["replicas"].items():
                if meta["state"] != "active":
                    continue
                pool = mgr._pools.get(r)
                backlog = 0
                if pool is not None:
                    backlog = sum(
                        1 for q in pool["requests"].values()
                        if q["status"] in ("pending", "inflight"))
                out[r] = {"interactive_p95": float(self.group_pressure),
                          "n": 8, "backlog": backlog}
        return out

    def op_sdfs(self, client: str) -> None:
        self._serial += 1
        name = f"f{self._serial}"
        blob = f"blob-{self.seed}-{self._serial}".encode()
        try:
            v = self.stores[client].put_bytes(name, blob)
        except (StoreError, TransportError):
            return
        # holders-at-ack for the ring-RF invariant, read straight off the
        # acting master's metadata (in-process, NO extra network traffic —
        # an ls RPC here would consume the net's chaos rng and shift every
        # existing seed's schedule)
        master = self.members[client].acting_master()
        store = self.stores[master]
        with store._meta_lock:
            holders = frozenset(store._locations.get(name, set()))
        self.sdfs_acked.append((name, v, blob, holders))

    # -- fault ops --------------------------------------------------------

    def op_partition(self) -> None:
        a, b = self.rng.sample(self.cfg.hosts, 2)
        self.net.partition(a, b)

    def op_isolate(self, host: str | None = None) -> None:
        h = host or self.rng.choice(self.cfg.hosts)
        for x in self.cfg.hosts:
            if x != h:
                self.net.partition(h, x)

    def op_oneway(self) -> None:
        a, b = self.rng.sample(self.cfg.hosts, 2)
        self.net.cut_oneway(a, b)

    def op_heal(self) -> None:
        self.net.heal_all()

    # -- pumping ----------------------------------------------------------

    def pump_membership(self, waves: int = 1, dt: float = 0.3) -> None:
        for _ in range(waves):
            for m in self.members.values():
                m.ping_once()
            self.clock.advance(dt)
            for m in self.members.values():
                m.monitor_once()

    def pump_work(self) -> None:
        for h in self.cfg.hosts:
            self.services[h].process_jobs_once()
        for h in self.cfg.hosts:
            if self.members[h].is_acting_master:
                self.services[h].monitor_stragglers_once()
            # multi-owner control plane (ISSUE 15): EVERY host pumps its
            # manager — pool owners drive their own scopes' dispatch and
            # WAL shipping, not just the master (pump_once no-ops on
            # hosts with nothing to do; replicate_once gates internally)
            self.managers[h].pump_once()
            self.failovers[h].replicate_once()

    def step(self) -> None:
        """One seeded schedule step: a workload or fault op, then a pump
        wave, then fence sampling."""
        self._steps_run += 1
        if self.autoscale:
            # scripted load curve: overload long enough to cross the
            # scale-out threshold, then idle so the group scales back in
            self.group_pressure = (5.0 if self._steps_run
                                   <= self.overload_steps else 0.0)
        if self.fail_slow:
            # scripted fail-slow window (ISSUE 20): the victim limps —
            # heartbeats still flow, so this is GRAY, not fail-stop —
            # through the middle of the schedule, then heals. The sweep
            # and the fault itself live entirely behind the flag so
            # existing seeds replay unshifted.
            if self._steps_run == 4:
                self.net.slow_host(self.slow_victim, 10.0)
            elif self._steps_run == self.overload_steps + 4:
                self.net.clear_slow(self.slow_victim)
            self.probe_sweep(self.slow_prober)
        r = self.rng.random()
        client = self.rng.choice(self.cfg.hosts)
        if r < 0.22:
            self.op_cnn(client)
        elif r < 0.44:
            # every extra draw is flag-gated: existing seeds' schedules
            # must not shift when the group/second-pool workload is off
            if self.autoscale and self.rng.random() < 0.5:
                self.op_lm_group(client)
            elif self.multi_pool and self.rng.random() < 0.5:
                self.op_lm_b(client)
            elif self.cluster_prefix and self.rng.random() < 0.5:
                self.op_lm_prefix(client)
            elif self.distserve and self.rng.random() < 0.5:
                self.op_lm_handoff(client)
            else:
                self.op_lm(client)
        elif r < 0.58:
            self.op_sdfs(client)
        elif r < 0.68:
            self.op_partition()
        elif r < 0.74:
            self.op_oneway()
        elif r < 0.80:
            self.op_isolate()
        elif r < 0.90:
            self.op_heal()
        # else: pure pump step
        self.pump_membership(waves=1)
        self.pump_work()
        self.record_fences()
        if self.fail_slow:
            self._sample_fail_slow()

    def _sample_fail_slow(self) -> None:
        """Per-step fail-slow invariant sampling: record that some
        ledger reached QUARANTINED, and trip a FALSE-LEAVE violation if
        a host keeps the victim out of its alive view for many
        consecutive steps while both ends' links are verifiably clean —
        the health plane diverting traffic must never suppress the
        heartbeats that would refute a drop-induced suspicion, and the
        fault itself advances no clock so it can never cause a timeout.
        One-off missing views are legal (datagram drop chaos); a LONG
        streak over clean links is the forged-LEAVE smell."""
        victim = self.slow_victim
        if not self.saw_quarantine:
            for m in self.members.values():
                if m.health.state(victim) == "quarantined":
                    self.saw_quarantine = True
                    break
        clean_victim = self.net.unperturbed(victim)
        for h in self.cfg.hosts:
            if h == victim:
                continue
            missing = (clean_victim and self.net.unperturbed(h)
                       and victim
                       not in self.members[h].members.alive_hosts())
            streak = self._leave_streak.get(h, 0) + 1 if missing else 0
            self._leave_streak[h] = streak
            if streak >= 8:
                self.violations.append(
                    f"false LEAVE: {h} kept fail-slow victim {victim} "
                    f"out of its alive view for {streak} clean-link "
                    f"steps (step {self._steps_run})")

    def run_schedule(self, steps: int = 40,
                     chaos: dict | None = None) -> None:
        if chaos:
            self.net.set_chaos(**chaos)
        for _ in range(steps):
            self.step()

    # -- convergence ------------------------------------------------------

    def final_master(self) -> str:
        acting = [h for h in self.cfg.hosts
                  if self.members[h].is_acting_master]
        assert len(acting) == 1, f"no unique acting master: {acting}"
        return acting[0]

    def converge(self, deadline_s: float = 20.0) -> float:
        """Heal everything and pump until all surviving work is terminal.
        Returns wall-clock seconds spent converging."""
        t0 = time.monotonic()
        self.net.heal_all()
        self.net.clear_chaos()
        self.net.flush_held()
        for h in self.cfg.hosts:
            self.net.revive(h)
        while True:
            self.pump_membership(waves=2)
            self.pump_work()
            for h in self.cfg.hosts:
                self.services[h].join_reassign_dispatch(timeout=1.0)
                self.stores[h].join_repair(timeout=1.0)
            self.record_fences()
            if self._settled():
                return time.monotonic() - t0
            if time.monotonic() - t0 > deadline_s:
                raise AssertionError(
                    f"seed {self.seed}: no convergence in {deadline_s}s: "
                    f"{self._unsettled()}")
            time.sleep(0.02)    # real time for the lm watchdog / threads

    def _surviving_cnn(self):
        """Acked queries present in the final master's journal lineage
        (a doomed minority-master ack books a model name the surviving
        journal never saw — a lost ack, the shape client idempotent
        retries exist for). Keyed on the BOOKING: results alone can leak
        into the survivor from workers finishing a deposed master's
        dispatches (`_handle_result` observes, never rejects), and such a
        query has no tasks to ever flip query_done."""
        m = self.services[self.final_master()]
        return [(model, q, lo, hi) for model, q, lo, hi in self.cnn_acked
                if m.scheduler.book.tasks_for_query(model, q)]

    def _pool_owner(self, name: str) -> str:
        """The host whose manager holds ``name``'s journal NOW: the final
        master's gossiped claim if its holder is alive, else the master
        itself (pre-ownership fallback). Every owner-aware read — drains,
        settle checks, invariant sweeps — goes through here instead of
        assuming the master holds every pool (ISSUE 15)."""
        fm = self.final_master()
        o = self.members[fm].owners.owner(pool_scope(name))
        if o is not None and o in self.members[fm].members.alive_hosts():
            return o
        return fm

    def _surviving_lm(self):
        mgr = self.managers[self._pool_owner(self.LM_POOL)]
        with mgr._lock:
            pool = mgr._pools.get(self.LM_POOL)
            rids = set(pool["requests"]) if pool else set()
            done = pool["done_total"] if pool else 0
        return rids, done

    def _unsettled(self) -> list[str]:
        out = []
        m = self.services[self.final_master()]
        for model, q, lo, hi in self._surviving_cnn():
            if not (m.query_done(model, q) or m.query_failed(model, q)):
                out.append(f"cnn {model} q{q}")
        pools = [("lm", self.LM_POOL)]
        if self.multi_pool:
            pools.append(("lmB", self.LM_POOL_B))
        for tag, pname in pools:
            mgr = self.managers[self._pool_owner(pname)]
            with mgr._lock:
                pool = mgr._pools.get(pname)
                if pool is None:
                    continue
                if pool["node"] is None:
                    out.append(f"{tag} pool unplaced")
                for rid, r in pool["requests"].items():
                    if r["status"] in ("pending", "inflight"):
                        out.append(f"{tag} rid {rid} {r['status']}")
        for gname in (self.LM_GROUP, self.LM_GROUP_D):
            mgr = self.managers[self._pool_owner(gname)]
            with mgr._lock:
                g = mgr._groups.get(gname)
                if g is None:
                    continue
                replicas = list(g["replicas"])
                placed = [r for r in replicas
                          if (mgr._pools.get(r) or {}).get("node")]
                if not placed:
                    out.append(f"group {gname} has no placed replica")
                for r in replicas:
                    rpool = mgr._pools.get(r)
                    if rpool is None:
                        continue
                    for rid, q in rpool["requests"].items():
                        if q["status"] in ("pending", "inflight"):
                            out.append(f"grp {r} rid {rid} {q['status']}")
        if self.fail_slow:
            # probation must HEAL once the fault clears: converge ends
            # only when no ledger still watches anyone — quarantine is a
            # verdict about a fault, not a permanent exile
            for h in self.cfg.hosts:
                w = self.members[h].health.watched()
                if w:
                    out.append(f"health {h} watches {sorted(w)}")
        return out

    def _settled(self) -> bool:
        acting = [h for h in self.cfg.hosts
                  if self.members[h].is_acting_master]
        if len(acting) != 1:
            return False
        # membership must re-converge too: every host sees every host
        # alive again (false LEAVEs refuted post-heal) — settling on work
        # completion alone would snapshot views mid-refutation
        full = set(self.cfg.hosts)
        for h in self.cfg.hosts:
            if set(self.members[h].members.alive_hosts()) != full:
                return False
        return not self._unsettled()

    # -- invariants -------------------------------------------------------

    def drain_lm(self) -> list[dict]:
        """Poll the surviving journal through the client path, recording
        per-completion delivery counts (token tuple = logical request
        identity, since every prompt is serial-unique)."""
        got = []
        names = ([self.LM_POOL]
                 + ([self.LM_POOL_B] if self.multi_pool else [])
                 + ([self.LM_GROUP] if self.autoscale else [])
                 + ([self.LM_GROUP_D] if self.distserve else []))
        for _ in range(3):
            for name in list(names):
                try:
                    out = self._client_control("n3", {"verb": "lm_poll",
                                                      "name": name})
                except RuntimeError as e:
                    # the pool died with a doomed lineage (created but
                    # never replicated before the master was deposed):
                    # nothing to drain — its acks were lost, never wrong
                    if "pool" in str(e):
                        names.remove(name)
                        continue
                    raise
                for c in out.get("completions", ()):
                    key = tuple(c["tokens"])
                    self.lm_delivered[key] = (
                        self.lm_delivered.get(key, 0) + 1)
                    self.lm_delivered_pool[key] = name
                    got.append(c)
            if not names:
                break
            self.pump_work()
        return got

    def span_dump(self) -> dict[str, list[dict]]:
        """Every host's current span window (ISSUE 6: chaos-causal
        dumps) — the raw material `tools/trace_export.py` turns into a
        Perfetto timeline of the failing schedule."""
        return {h: s.dump() for h, s in self.spans.items()}

    def check_invariants(self) -> dict:
        """Assert every global invariant; returns a summary dict. On any
        trip the full per-host span dump is snapshotted into
        ``last_span_dump`` BEFORE the assertion propagates, so the failing
        request's trace (the one named in the assertion message) can be
        pulled out and exported without replaying the seed."""
        try:
            return self._check_invariants()
        except AssertionError:
            self.last_span_dump = self.span_dump()
            raise

    def _check_invariants(self) -> dict:
        assert not self.violations, self.violations
        for e, owners in self.epoch_owners.items():
            assert len(owners) <= 1, \
                f"epoch {e} owned by {sorted(owners)} (split brain)"
        for e, hosts in self.acting_by_epoch.items():
            assert len(hosts) <= 1, \
                f"epoch {e} acted by {sorted(hosts)} (split brain)"
        for (scope, e), owners in self.scope_owners.items():
            assert len(owners) <= 1, \
                f"scope {scope} epoch {e} owned by {sorted(owners)} " \
                f"(per-pool split brain)"
        # ownership claims (ISSUE 15): at most one claimant per
        # (scope, seq) across every host's gossiped view, the FIRST
        # claim lands exactly on the rendezvous placement, and with a
        # second pool the owners genuinely spread over >1 host
        for (scope, seq), owners in self.claim_owners.items():
            assert len(owners) <= 1, \
                f"scope {scope} claim seq {seq} by {sorted(owners)} " \
                f"(ownership split brain)"
        for scope, want in self.expected_owners.items():
            got = self.claim_owners.get((scope, 1))
            if got:
                assert got == {want}, \
                    f"scope {scope} first claim {sorted(got)} != " \
                    f"rendezvous placement {want}"
        if self.multi_pool:
            assert len(set(self.expected_owners.values())) >= 2, \
                f"owner spread: every scope placed on one host " \
                f"{self.expected_owners}"
        # membership converged: every alive host agrees on the alive set
        views = {h: tuple(self.members[h].members.alive_hosts())
                 for h in self.cfg.hosts}
        assert len(set(views.values())) == 1, views
        # CNN: exact result sets, no duplicate records
        m = self.services[self.final_master()]
        survived = self._surviving_cnn()
        for model, q, lo, hi in survived:
            if m.query_failed(model, q):
                continue        # terminal (move-cap) — still exactly-once
            recs = m.results(model, q)
            names = [r[0] for r in recs]
            assert len(names) == len(set(names)) == hi - lo + 1, \
                f"{model} q{q}: {len(names)} records for {hi - lo + 1}"
            assert set(names) == {f"test_{i}.JPEG"
                                  for i in range(lo, hi + 1)}
        # LM: exactly one terminal state per surviving admitted request,
        # token-exact completions, at-most-once delivery
        self.drain_lm()
        rids, done_total = self._surviving_lm()
        by_tokens = {tuple(lm_tokens(a["prompt"], a["seed"],
                                     a["max_new"])): a
                     for a in self.lm_attempted}
        for key, n in self.lm_delivered.items():
            assert n == 1, f"completion delivered {n}x: {key}"
            assert key in by_tokens, f"tokens never submitted: {key}"
            # cross-pool isolation: the completion surfaced from the pool
            # its tokens were submitted to — a deposed pool-A owner whose
            # outbox leaked into pool B would trip here
            want_pool = by_tokens[key].get("pool", self.LM_POOL)
            got_pool = self.lm_delivered_pool.get(key, want_pool)
            assert got_pool == want_pool, \
                f"completion crossed pools: submitted to {want_pool}, " \
                f"delivered by {got_pool}: {key}"
        # SDFS: surviving puts read back exactly, and ring re-replication
        # kept every surviving version at full strength — alive holders
        # >= min(replication_factor, holders-at-ack)
        store = self.stores[self.final_master()]
        alive_now = set(self.members[self.final_master()]
                        .members.alive_hosts())
        sdfs_survived = 0
        for name, version, blob, holders in self.sdfs_acked:
            try:
                got, v = store.get_bytes(name, version=version)
            except StoreError:
                continue        # doomed-lineage ack (lost, never wrong)
            assert got == blob, f"{name} v{version} corrupt"
            sdfs_survived += 1
            have = {h for h in self.cfg.hosts
                    if version in self.stores[h].local.files().get(name, [])}
            want = min(self.cfg.replication_factor,
                       len(holders) if holders else 1, len(alive_now))
            assert len(have & alive_now) >= max(want, 1), \
                f"{name} v{version}: alive holders " \
                f"{sorted(have & alive_now)} < {want} (RF not restored)"
        # replica group: the scaling journal itself is an invariant
        # surface — exactly-once decisions, fenced epochs, no replica
        # double-spawned by a replayed decision (ISSUE 11)
        grp_summary: dict = {}
        if self.autoscale:
            mgr = self.managers[self._pool_owner(self.LM_GROUP)]
            with mgr._lock:
                g = mgr._groups.get(self.LM_GROUP)
                gview = (None if g is None
                         else {"decisions": [dict(d)
                                             for d in g["decisions"]],
                               "next_seq": g["next_seq"],
                               "replicas": {r: dict(m) for r, m
                                            in g["replicas"].items()}})
            assert gview is not None, "replica group lost from journal"
            seqs = [d["seq"] for d in gview["decisions"]]
            assert seqs == sorted(set(seqs)), \
                f"scale decisions not strictly increasing: {seqs}"
            assert not seqs or seqs[-1] == gview["next_seq"] - 1, \
                f"decision journal truncated wrong: {seqs[-6:]} " \
                f"vs next_seq {gview['next_seq']}"
            spawned = [d["replica"] for d in gview["decisions"]
                       if d["action"] == "spawn"]
            assert len(spawned) == len(set(spawned)), \
                f"replica double-spawned: {spawned}"
            eps = [int(d["epoch"][0]) for d in gview["decisions"]]
            assert eps == sorted(eps), \
                f"scale-decision epochs regressed: {eps}"
            # every replica the journal believes in must be a real
            # {group}@r{i} name within the minted range
            for r in gview["replicas"]:
                idx = LMPoolManager._replica_index(r)
                assert 0 <= idx, f"malformed replica name {r!r}"
            # forecast determinism (ISSUE 20 satellite): the decision
            # rows carry the Holt predicted_rate that justified them —
            # digesting the full journal lets the soak driver replay the
            # seed and assert the forecast reproduced bit-for-bit
            blob = json.dumps(gview["decisions"], sort_keys=True)
            grp_summary = {"grp_acked": len(self.grp_acked),
                           "grp_replicas": len(gview["replicas"]),
                           "grp_decisions": gview["next_seq"],
                           "grp_decision_digest":
                               hashlib.sha256(blob.encode()).hexdigest()[:16]}
        # cluster prefix cache (ISSUE 17): inline content checks landed
        # in self.violations (asserted empty above); the summary carries
        # the aggregate fake-tier gauges so soak JSON shows the workload
        # actually exercised remote hits, not just cold misses
        prefix_summary: dict = {}
        if self.cluster_prefix:
            loops = [loop for ctl in self.controls.values()
                     for loop in ctl._loops.values()
                     if loop.get("cp") is not None]
            prefix_summary = {
                "lmp_acked": len(self.lmp_acked),
                "prefix_remote_hits": sum(x["remote_hits"]
                                          for x in loops),
                "prefix_published": sum(x["published"] for x in loops),
                "prefix_warmed": sum(x["warmed"] for x in loops)}
        # DistServe handoff (ISSUE 18): every handed-off request reached
        # a TERMINAL handoff state (adopted or fallback) by convergence —
        # a request stuck "prefilling"/"shipping" would mean the replay
        # machinery lost a ship edge. Content corruption landed in
        # self.violations (asserted empty above) via the adopt-side
        # KVC1 expect_tokens + chunk_content checks.
        ds_summary: dict = {}
        if self.distserve:
            mgr = self.managers[self._pool_owner(self.LM_GROUP_D)]
            with mgr._lock:
                g = mgr._groups.get(self.LM_GROUP_D)
                assert g is not None, "distserve group lost from journal"
                roles = {m["role"] for m in g["replicas"].values()}
                rc = dict(g["route_counts"])
                states: dict[str, int] = {}
                for r in list(g["replicas"]):
                    rpool = mgr._pools.get(r)
                    if rpool is None:
                        continue
                    for rid, q in rpool["requests"].items():
                        hop = q.get("handoff")
                        if not hop:
                            continue
                        st = hop.get("state")
                        states[st] = states.get(st, 0) + 1
                        assert st in ("adopted", "fallback"), \
                            f"handoff {r}:{rid} non-terminal at " \
                            f"convergence: {st!r}"
            assert {"prefill", "decode"} <= roles, \
                f"distserve group lost its role split: {sorted(roles)}"
            shipped = sum(x["shipped"] for ctl in self.controls.values()
                          for x in ctl._loops.values())
            adopted = sum(x["adopted"] for ctl in self.controls.values()
                          for x in ctl._loops.values())
            ds_summary = {
                "lmh_acked": len(self.lmh_acked),
                "handoff_routed": rc.get("handoff", 0),
                "handoff_adopted": states.get("adopted", 0),
                "handoff_fallback": states.get("fallback", 0),
                "handoff_blocks_shipped": shipped,
                "handoff_blocks_adopted": adopted}
        # gray failure (ISSUE 20): the differential plane must have
        # QUARANTINED the scripted limping victim (heartbeats alive the
        # whole time — the false-LEAVE streak check above feeds
        # self.violations), and every ledger must be back to all-healthy
        # after the fault cleared (probation heals; also a converge
        # gate, re-asserted here so the summary can't lie)
        fs_summary: dict = {}
        if self.fail_slow:
            assert self.saw_quarantine, \
                f"fail-slow victim {self.slow_victim} never quarantined"
            for h in self.cfg.hosts:
                w = self.members[h].health.watched()
                assert not w, \
                    f"{h} still watches {sorted(w)} after fault clear"
            fs_summary = {"slow_victim": self.slow_victim,
                          "quarantine_seen": True}
        pool_epochs: dict[str, int] = {}
        for scope, e in self.scope_owners:
            pool_epochs[scope] = max(pool_epochs.get(scope, 0), e)
        # final ownership map + total claim movement (seq 1 is the
        # placement claim; every later seq is an adoption move)
        fm_owners = self.members[self.final_master()].owners
        final_owners = {s: fm_owners.owner(s) for s in fm_owners.scopes()}
        max_seq: dict[str, int] = {}
        for scope, seq in self.claim_owners:
            max_seq[scope] = max(max_seq.get(scope, 1), seq)
        owner_moves = sum(s - 1 for s in max_seq.values())
        return {"cnn_acked": len(self.cnn_acked),
                "cnn_survived": len(survived),
                "lm_acked": len(self.lm_acked),
                "lmb_acked": len(self.lmb_acked),
                "lm_delivered": len(self.lm_delivered),
                "sdfs_acked": len(self.sdfs_acked),
                "sdfs_survived": sdfs_survived,
                "epochs": max(self.epoch_owners, default=0),
                "pool_epochs": pool_epochs,
                "scope_owners": final_owners,
                "owner_moves": owner_moves,
                "hosts": len(self.cfg.hosts),
                "final_master": self.final_master(),
                **grp_summary, **prefix_summary, **ds_summary,
                **fs_summary}


def run_seeded_schedule(seed: int, data_dir: str, steps: int = 40,
                        chaos: dict | None = None,
                        prefill_chunk: int = 0,
                        n_model: int = 1,
                        autoscale: bool = False,
                        multi_pool: bool = False,
                        n_hosts: int = 5,
                        cluster_prefix: bool = False,
                        distserve: bool = False,
                        fail_slow: bool = False) -> dict:
    """One full seeded chaos run: schedule -> converge -> invariants.
    Returns the invariant summary plus convergence time.
    ``prefill_chunk`` rides the managed pool's lm_serve spec (ISSUE 7):
    the fake tier defers long-prompt completions by a poll round, so the
    schedule exercises journaled specs + watchdog retries against a pool
    with in-flight chunked admissions. ``autoscale`` adds a replica
    group with scripted overload→underload pressure (ISSUE 11): the
    autoscaler's spawn/retire decisions ride the same fault schedule and
    the group's scaling journal joins the invariant surface.
    ``multi_pool`` serves a SECOND concurrent managed pool and
    ``n_hosts`` scales the cluster (ISSUE 14): per-pool fence scopes,
    scoped adoption, and cross-pool isolation join the invariant surface,
    certified at 50-100 hosts by the soak driver.
    ``cluster_prefix`` serves the first pool with the cluster prefix
    cache on (ISSUE 17): a shared-head workload publishes/remote-hits
    real KVC1 blobs on the real SDFS ring, with inline wrong-token /
    double-prefill checks feeding the violations ledger.
    ``distserve`` serves a role-split replica group with a KV block pool
    (ISSUE 18): long-prompt submissions route in handoff mode — the
    manager journals prefilling→shipping→adopted edges and ships real
    KVC1 blobs between the fake loops; deaths mid-handoff must replay
    the ship or fall back, never lose or double the request.
    ``fail_slow`` runs the gray-failure schedule (ISSUE 20): one scripted
    limping victim (synthesized latency, heartbeats alive), a fixed
    prober sampling the whole fleet, quarantine-without-LEAVE and
    probation-heals invariants on top of everything above."""
    c = ChaosCluster(seed, data_dir, n_hosts=n_hosts,
                     prefill_chunk=prefill_chunk,
                     n_model=n_model, autoscale=autoscale,
                     multi_pool=multi_pool,
                     cluster_prefix=cluster_prefix,
                     distserve=distserve,
                     fail_slow=fail_slow)
    try:
        c.run_schedule(steps=steps,
                       chaos=chaos if chaos is not None
                       else {"drop": 0.05, "dup": 0.03, "delay": 0.10,
                             "seed": seed})
        convergence_s = c.converge()
        out = c.check_invariants()
    except Exception as e:
        # any failure — invariant trip or convergence timeout — carries
        # the cluster's span windows out with it (chaos-causal dump: the
        # failing request's trace is in here, replayable from the seed)
        if c.last_span_dump is None:
            c.last_span_dump = c.span_dump()
        e.span_dump = c.last_span_dump
        raise
    out["convergence_s"] = round(convergence_s, 3)
    out["seed"] = seed
    out["spans_recorded"] = sum(s.recorded_total()
                                for s in c.spans.values())
    return out
