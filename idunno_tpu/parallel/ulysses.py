"""Ulysses-style sequence parallelism: all-to-all head/sequence re-sharding.

The second canonical long-context strategy next to ring attention
(`idunno_tpu.parallel.ring_attention`): instead of rotating K/V blocks
around the ring, one ``all_to_all`` over ICI re-shards Q/K/V from
sequence-sharded [B, T/p, H, D] to head-sharded [B, T, H/p, D]; each device
then runs ordinary full attention over the complete sequence for its head
group, and a second ``all_to_all`` restores sequence sharding. Communication
is two all-to-alls of activation size (independent of T²), and the attention
itself needs no online-softmax bookkeeping.

Trade-off vs ring attention: Ulysses needs ``num_heads`` divisible by the
axis size and materializes full-T attention per head group (memory
O(T²/heads-group) unless paired with a flash kernel); ring attention has no
head constraint and O((T/p)²) score blocks. Both are exposed through the
same ``attn_fn`` plug on `idunno_tpu.models.transformer.TransformerLM`.

The reference system has no sequence axis at all (image CNNs,
SURVEY.md §5 "long-context") — these modules are the TPU framework's
equivalent of its only scaling axis, query-range sharding
(`mp4_machinelearning.py:516-536`), applied to sequence length.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from idunno_tpu.parallel.mesh import DATA_AXIS
from idunno_tpu.parallel._compat import shard_map
from idunno_tpu.parallel.ring_attention import full_attention


def _ulysses_shard(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   axis_name: str, causal: bool, local_attn) -> jnp.ndarray:
    """Per-shard body. q/k/v: [B, T_local, H, D] → same shape."""
    # seq-sharded → head-sharded: split heads into p groups, gather sequence.
    def to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)   # [B, T, H/p, D]
    out = local_attn(qh, kh, vh, causal=causal)
    return to_seq(out)                                    # [B, T/p, H, D]


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Mesh, *, seq_axis: str = DATA_AXIS,
                      causal: bool = False,
                      local_attn=full_attention) -> jnp.ndarray:
    """Attention with the sequence dim sharded over ``seq_axis``.

    q/k/v: [B, T, H, D] global, T divisible by the axis size, H divisible by
    the axis size. Returns [B, T, H, D] with the same sharding — a drop-in
    for ``ring_attention`` where the head count allows it.

    ``local_attn`` is the within-shard attention over the full sequence for
    the local head group — ``full_attention`` by default, or the Pallas
    `idunno_tpu.ops.flash_attention.flash_attention` to also avoid the
    O(T²) score materialization on-chip.
    """
    p = mesh.shape[seq_axis]
    if q.shape[2] % p:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by the "
            f"{seq_axis!r} axis size ({p}); use ring_attention instead")
    spec = P(None, seq_axis, None, None)
    fn = functools.partial(_ulysses_shard, axis_name=seq_axis, causal=causal,
                           local_attn=local_attn)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)
