"""Ring attention — sequence/context parallelism over the mesh.

The reference has no sequence dimension anywhere (image CNNs only,
SURVEY.md §5 "long-context"), but a complete TPU framework must scale the
sequence axis the way the reference scales its batch axis. This implements
blockwise ring attention (Liu et al.-style): Q/K/V are sharded along the
sequence across mesh devices; each device computes attention of its local
queries against one K/V block at a time while K/V blocks rotate around the
ring via ``ppermute`` over ICI, accumulating with an online (flash-style)
softmax. Peak memory per device is O(T/p · T/p) instead of O(T²), and the
K/V transfer overlaps compute around the ring.

Pure JAX: `shard_map` + `ppermute` + `fori_loop`, so XLA schedules the
collective/compute overlap — no hand-written RDMA.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from idunno_tpu.parallel.mesh import DATA_AXIS
from idunno_tpu.parallel._compat import pvary, shard_map


def _ring_attention_shard(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          *, axis_name: str, p: int, causal: bool,
                          scale: float) -> jnp.ndarray:
    """Per-shard body. q/k/v: [B, T_local, H, D]. ``p`` is the concrete
    ring size (= mesh.shape[axis_name]; jax.lax.axis_size is not available
    on every supported jax)."""
    my = jax.lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    perm = [(j, (j + 1) % p) for j in range(p)]

    q_pos = my * t_q + jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)

    def step(i, carry):
        o, m, l, k_blk, v_blk = carry
        # whose K/V block do we hold after i rotations? (blocks move +1 in
        # ring index per step, so we hold (my - i) mod p's block)
        src = (my - i) % p
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
        if causal:
            k_pos = src * t_k + jax.lax.broadcasted_iota(
                jnp.int32, (t_q, t_k), 1)
            mask = q_pos >= k_pos
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # guard fully-masked rows: exp(-inf - -inf) -> use safe max
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        alpha = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m - m_safe))
        probs = jnp.exp(scores - m_safe[..., None])
        l_new = l * alpha + probs.sum(axis=-1)
        o_new = (o * alpha[..., None]
                 + jnp.einsum("bhqk,bkhd->bhqd", probs, v_blk))
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return o_new, m_new, l_new, k_next, v_next

    o0 = jnp.zeros((b, h, t_q, d), jnp.float32)
    m0 = jnp.full((b, h, t_q), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t_q), jnp.float32)
    # mark the replicated initial carry as device-varying so the loop
    # carry type matches its output (shard_map vma typing)
    o0, m0, l0 = (pvary(x, axis_name) for x in (o0, m0, l0))
    o, m, l, _, _ = jax.lax.fori_loop(
        0, p, step, (o0, m0, l0, k.astype(jnp.float32),
                     v.astype(jnp.float32)))
    l = jnp.where(l == 0.0, 1.0, l)              # fully-masked rows -> 0 out
    out = (o / l[..., None]).astype(q.dtype)
    return jnp.einsum("bhqd->bqhd", out)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, *, seq_axis: str = DATA_AXIS,
                   causal: bool = False) -> jnp.ndarray:
    """Multi-head attention with the sequence dim sharded over ``seq_axis``.

    q/k/v: [B, T, H, D] global shape, T divisible by the axis size.
    Returns [B, T, H, D] with the same sharding.
    """
    scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(None, seq_axis, None, None)
    fn = functools.partial(_ring_attention_shard, axis_name=seq_axis,
                           p=mesh.shape[seq_axis], causal=causal, scale=scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)


def full_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   *, causal: bool = False) -> jnp.ndarray:
    """Single-device reference implementation (for tests and small inputs)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        t_q, t_k = scores.shape[-2], scores.shape[-1]
        mask = (jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
                >= jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
