"""Expert parallelism: switch-style top-1 MoE dispatch via all_to_all.

The reference's notion of "experts" is its two independent model jobs
fair-sharing the worker pool (`mp4_machinelearning.py:501-539`); within one
model it has no conditional computation. This module adds the real thing
for the TPU framework's sequence models: tokens are routed to the top-1
expert, packed into fixed ``[E, C, d]`` capacity buffers (static shapes —
XLA-friendly; overflow tokens are dropped, the standard switch trade-off),
exchanged over ICI with one ``all_to_all`` so each mesh shard holds only its
``E/p`` experts' tokens, run through the local expert FFNs, and returned by
the mirror ``all_to_all``, with gate-weighted combine back into sequence
order.

Used by `idunno_tpu.models.moe.SwitchFFN`, which also provides the dense
(every-device-holds-every-expert) path for single-device runs and as the
ground truth the EP path is tested against.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from idunno_tpu.parallel._compat import shard_map

EXPERT_AXIS = "expert"


def switch_dispatch(gate_idx: jnp.ndarray, gate_w: jnp.ndarray,
                    n_experts: int, capacity: int
                    ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Top-1 dispatch/combine tensors for local tokens.

    gate_idx [n] int, gate_w [n] float → dispatch one-hot [n, E, C] and
    combine (= dispatch · gate weight) [n, E, C]. Tokens beyond an expert's
    capacity get all-zero rows (dropped).
    """
    onehot = jax.nn.one_hot(gate_idx, n_experts, dtype=jnp.float32)  # [n, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0                  # [n, E]
    in_cap = (pos >= 0) & (pos < capacity)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=jnp.float32)                       # [n,E,C]
    dispatch = pos_oh * in_cap[..., None].astype(jnp.float32)
    combine = dispatch * gate_w[:, None, None]
    return dispatch, combine


def expert_parallel_apply(expert_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                          stacked_params: Any, x: jnp.ndarray,
                          gate_idx: jnp.ndarray, gate_w: jnp.ndarray,
                          mesh: Mesh, *, axis: str = EXPERT_AXIS,
                          capacity: int) -> jnp.ndarray:
    """Run the MoE layer with experts sharded over ``axis``.

    x [N, d] and gates [N] are token-sharded over the same axis (N divisible
    by the axis size); stacked_params leaves are [E, ...] with E divisible by
    the axis size. Returns [N, d], token-sharded as the input.
    """
    p = mesh.shape[axis]
    n_experts = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_experts % p:
        raise ValueError(f"{n_experts} experts not divisible by "
                         f"{axis!r} axis size {p}")

    def body(params_sh, x_l, idx_l, w_l):
        # params_sh leaves: [E/p, ...] — this shard's experts.
        dispatch, combine = switch_dispatch(idx_l, w_l, n_experts, capacity)
        buf = jnp.einsum("nec,nd->ecd", dispatch, x_l)        # [E, C, d]
        # group tokens by owning shard: [E/p, p*C, d]
        buf = jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=1,
                                 tiled=True)
        out = jax.vmap(expert_fn)(params_sh, buf)             # [E/p, p*C, d]
        out = jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                                 tiled=True)                  # [E, C, d]
        return jnp.einsum("ecd,nec->nd", out, combine)

    pspec = jax.tree.map(lambda _: P(axis), stacked_params)
    return shard_map(body, mesh=mesh,
                     in_specs=(pspec, P(axis), P(axis), P(axis)),
                     out_specs=P(axis))(stacked_params, x, gate_idx, gate_w)
