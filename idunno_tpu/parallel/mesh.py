"""Device-mesh construction.

The reference's "mesh" is the 10-VM host ring (`utils.py:57-61`) with raw
sockets between nodes. The TPU-native worker set is the chips of a pod slice
arranged in a `jax.sharding.Mesh`; data movement between them is XLA
collectives over ICI, inserted by the compiler from sharding annotations —
not hand-written sends (SURVEY.md §5 "distributed communication backend").

Axis conventions:
    data   — batch-dimension data parallelism (the reference's only strategy:
             query-range sharding, `mp4_machinelearning.py:516-536`)
    model  — optional tensor parallelism for wide layers
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(n_data: int, n_model: int = 1,
              devices: list | None = None) -> Mesh:
    """Build a (data, model) mesh over ``devices`` (default: all local)."""
    devices = devices if devices is not None else jax.devices()
    need = n_data * n_model
    if need > len(devices):
        raise ValueError(f"mesh {n_data}x{n_model} needs {need} devices, "
                         f"have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def local_mesh(n_model: int = 1) -> Mesh:
    """Mesh over every visible device, data-parallel by default."""
    n = len(jax.devices())
    if n % n_model:
        raise ValueError(f"{n} devices not divisible by model axis {n_model}")
    return make_mesh(n // n_model, n_model)
