"""Device-mesh construction.

The reference's "mesh" is the 10-VM host ring (`utils.py:57-61`) with raw
sockets between nodes. The TPU-native worker set is the chips of a pod slice
arranged in a `jax.sharding.Mesh`; data movement between them is XLA
collectives over ICI, inserted by the compiler from sharding annotations —
not hand-written sends (SURVEY.md §5 "distributed communication backend").

Axis conventions:
    data   — batch-dimension data parallelism (the reference's only strategy:
             query-range sharding, `mp4_machinelearning.py:516-536`)
    model  — optional tensor parallelism for wide layers
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


class MeshShapeError(ValueError):
    """A requested (data, model) mesh shape cannot be built.

    Typed (not bare ValueError) so callers can branch on "the mesh itself
    is impossible" — wrong device count, non-dividing ``n_model``, or a
    model axis the attention-head geometry can't split over — separately
    from ordinary bad-argument errors. Carries the numbers that explain
    the refusal:

        n_devices  visible/offered device count (0 if not device-related)
        n_model    requested model-axis extent
        constraint one-line statement of the violated rule
    """

    def __init__(self, msg: str, *, n_devices: int = 0, n_model: int = 1,
                 constraint: str = ""):
        super().__init__(msg)
        self.n_devices = n_devices
        self.n_model = n_model
        self.constraint = constraint


def make_mesh(n_data: int, n_model: int = 1,
              devices: list | None = None) -> Mesh:
    """Build a (data, model) mesh over ``devices`` (default: all local)."""
    devices = devices if devices is not None else jax.devices()
    need = n_data * n_model
    if need > len(devices):
        raise MeshShapeError(
            f"mesh {n_data}x{n_model} needs {need} devices, "
            f"have {len(devices)}",
            n_devices=len(devices), n_model=n_model,
            constraint=f"n_data*n_model <= {len(devices)} devices")
    grid = np.asarray(devices[:need]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def local_mesh(n_model: int = 1) -> Mesh:
    """Mesh over every visible device, data-parallel by default."""
    n = len(jax.devices())
    if n_model < 1 or n % n_model:
        raise MeshShapeError(
            f"{n} devices not divisible by model axis {n_model}",
            n_devices=n, n_model=n_model,
            constraint=f"n_model must divide {n} devices")
    return make_mesh(n // n_model, n_model)


def check_head_divisibility(num_heads: int, n_model: int) -> None:
    """Attention-head constraint for a model-axis of ``n_model``: Q heads
    must split evenly (Megatron column-parallel QKV). Raises the typed
    MeshShapeError naming the constraint; KV heads are handled separately
    (divide-or-replicate, see `parallel/sharding.py:lm_tp_specs`)."""
    if n_model > 1 and num_heads % n_model:
        raise MeshShapeError(
            f"num_heads={num_heads} not divisible by model axis "
            f"{n_model}",
            n_model=n_model,
            constraint=f"num_heads % n_model == 0 "
                       f"(got {num_heads} % {n_model})")


# -- multi-host bring-up ----------------------------------------------------
#
# The reference "scales" by humans starting one process per VM against a
# hardcoded IP table (`README.md:10-29`, `utils.py:70-92`). The TPU-native
# equivalent is the JAX multi-process runtime: every host process calls
# `jax.distributed.initialize` against one coordinator address (DCN), after
# which `jax.devices()` is the GLOBAL device set and a mesh over it spans
# hosts — collectives ride ICI within a slice and DCN across slices, all
# inserted by XLA from the same sharding annotations as the single-host path.

def initialize_distributed(coordinator_address: str,
                           num_processes: int | None = None,
                           process_id: int | None = None,
                           local_device_ids=None) -> None:
    """`jax.distributed.initialize` wrapper (idempotent): bring this process
    into the multi-host runtime. On TPU pods num_processes/process_id are
    inferred from the TPU metadata; on CPU/GPU fleets pass them explicitly
    (``python -m idunno_tpu --jax-coordinator host:port
    --jax-num-processes N --jax-process-id I``)."""
    try:                                   # already initialised: keep going
        from jax._src.distributed import global_state
        if getattr(global_state, "client", None) is not None:
            return
    except ImportError:                    # pragma: no cover - private API
        pass
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id,
            local_device_ids=local_device_ids)
    except RuntimeError as e:
        # jax raises "distributed.initialize should only be called once."
        msg = str(e).lower()
        if "already" not in msg and "once" not in msg:
            raise


def global_mesh(n_model: int = 1) -> Mesh:
    """(data, model) mesh over the GLOBAL device set (all processes); call
    after `initialize_distributed`. Each process runs the same program;
    arrays sharded over the data axis are globally sharded across hosts."""
    devices = jax.devices()                # global across processes
    n = len(devices)
    if n_model < 1 or n % n_model:
        raise MeshShapeError(
            f"{n} global devices not divisible by model axis {n_model}",
            n_devices=n, n_model=n_model,
            constraint=f"n_model must divide {n} global devices")
    return make_mesh(n // n_model, n_model, devices=devices)


def process_info() -> tuple[int, int]:
    """(process_index, process_count) — host identity inside the runtime."""
    return jax.process_index(), jax.process_count()
