"""JAX version-compat shims shared by the parallel modules.

One place for the API moves that affect shard_map-based code so the
ring/ulysses/pipeline/expert implementations can't drift apart:
  - ``shard_map`` graduated from jax.experimental to jax.* in v0.8
  - ``pvary`` was replaced by ``pcast(..., to="varying")`` in v0.9
"""
from __future__ import annotations

import jax

try:
    from jax import shard_map  # type: ignore  # noqa: F401  (jax >= 0.8)
except ImportError:            # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore # noqa: F401


def pvary(x, axis_name: str):
    """Mark a replicated value as device-varying along ``axis_name`` (needed
    to type shard_map loop carries whose inputs are replicated)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis_name,), to="varying")
    if hasattr(jax.lax, "pvary"):   # pragma: no cover - older jax
        return jax.lax.pvary(x, (axis_name,))
    return x                        # pragma: no cover - very old jax
