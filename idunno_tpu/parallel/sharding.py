"""Sharding policies.

Params are replicated across the data axis (each chip holds the full model in
HBM — the reference's whole-model-per-worker layout, `alexnet_resnet.py:18-22`,
done right); batches are sharded over the data axis so each chip computes its
contiguous slice of the query range. Optional tensor parallelism shards wide
kernels over the model axis.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from idunno_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) dim split over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch: Any) -> Any:
    """Place a host batch on the mesh, leading dim over the data axis."""
    return jax.device_put(batch, batch_sharding(mesh))


def replicate(mesh: Mesh, tree: Any) -> Any:
    """Replicate a pytree (model variables) across the whole mesh."""
    return jax.device_put(tree, replicated_sharding(mesh))


def tp_param_spec(path: tuple, leaf: Any) -> P:
    """Tensor-parallel PartitionSpec for a param leaf: shard the last
    (output-features) dim of large Dense kernels over the model axis,
    replicate everything else. Used by the optional TP engine mode."""
    name = "/".join(str(p) for p in path)
    if leaf.ndim >= 2 and leaf.shape[-1] % 2 == 0 and "fc" in name and leaf.size > 1 << 20:
        return P(*([None] * (leaf.ndim - 1) + [MODEL_AXIS]))
    return P()


# -- LM tensor parallelism (stacked scanned layout) -------------------------
#
# Megatron-style intra-layer split (Shoeybi et al. 2019; Pope et al. MLSys
# 2023 for the inference variant): Q/K/V and mlp_up are COLUMN-parallel
# (output heads / hidden features sharded over the model axis), out and
# mlp_down are ROW-parallel (contraction dim sharded → one psum each), so
# GSPMD inserts exactly TWO collectives per block — and because the specs
# ride the *stacked* `[depth, ...]` leaves, those collectives live inside
# the scan body of the ONE `lax.scan`, not per unrolled layer. The UNEMBED
# (head) is COLUMN-sharded over the vocab axis (ISSUE 16): the fused
# sampling tail resolves greedy/sampled/filtered picks from per-shard
# partial stats (`ops/sampling.py:sample_keep_mask`), so the [S, vocab]
# logits never all-gather — when the vocab doesn't divide n_model,
# `_sanitize` degrades the head to replicated and everything still
# serves. The EMBEDDING stays replicated (a [S, 1] token lookup saves
# nothing sharded, and the logits stay bit-identical across n_model
# everywhere the math is elementwise — the token-exactness tests compare
# streams across mesh shapes).
#
# GQA rule: Q heads MUST divide n_model (`mesh.check_head_divisibility`);
# KV heads divide-or-replicate — when `num_kv_heads % n_model != 0` the
# k/v kernels and the KV cache stay replicated while Q still shards
# (GSPMD reshards at the grouped einsum; correct, just more traffic).

_PATH_STR_KEYS = ("key", "name", "idx")


def _path_names(path: tuple) -> list[str]:
    out = []
    for p in path:
        for attr in _PATH_STR_KEYS:
            v = getattr(p, attr, None)
            if isinstance(v, str):
                out.append(v)
                break
    return out


def _sanitize(spec: P, leaf: Any, n_model: int) -> P:
    """Clamp a wished-for spec to what the leaf can actually carry: drop
    the model axis from any dim the leaf lacks or that doesn't divide
    (QTensor scales have broadcast 1-dims; odd hidden sizes replicate)."""
    axes = list(spec) + [None] * (leaf.ndim - len(spec))
    axes = axes[:leaf.ndim]
    for i, ax in enumerate(axes):
        if ax is not None and leaf.shape[i] % n_model:
            axes[i] = None
    while axes and axes[-1] is None:
        axes.pop()
    return P(*axes)


def lm_tp_specs(params: Any, *, n_model: int,
                kv_shard: bool = True) -> Any:
    """PartitionSpec tree for a *stacked* scanned LM param tree
    (`stack_block_params` output: block leaves under "blocks" with a
    leading depth axis). QTensor leaves spec through their fields (q
    shards like its kernel, broadcast scale dims auto-replicate).
    ``kv_shard=False`` replicates k/v (GQA divide-or-replicate)."""
    M = MODEL_AXIS
    kernel_rules = {
        "q": P(None, None, M, None),            # [L, dim, H, hd]
        "k": P(None, None, M, None) if kv_shard else P(),
        "v": P(None, None, M, None) if kv_shard else P(),
        "out": P(None, M, None, None),          # [L, H, hd, dim]  (psum)
        "mlp_up": P(None, None, M),             # [L, dim, hidden]
        "mlp_down": P(None, M, None),           # [L, hidden, dim] (psum)
    }
    bias_rules = {
        "q": P(None, M, None),                  # [L, H, hd]
        "k": P(None, M, None) if kv_shard else P(),
        "v": P(None, M, None) if kv_shard else P(),
        "mlp_up": P(None, M),                   # [L, hidden]
    }

    def rule(path, leaf):
        if n_model <= 1 or not hasattr(leaf, "ndim"):
            return P()
        names = _path_names(path)
        if "head" in names:
            # unembed column-shards over the vocab (ISSUE 16): kernel
            # [dim, vocab] / bias [vocab]; non-dividing vocab degrades
            # to replicated via _sanitize
            if "kernel" in names:
                return _sanitize(P(None, M), leaf, n_model)
            if "bias" in names:
                return _sanitize(P(M), leaf, n_model)
            return P()
        if "blocks" not in names:
            return P()                          # embed/ln_f replicated
        # module name is the segment just before kernel/bias; QTensor
        # fields ("q"/"scale") come AFTER, so cut the path there first
        for kind, rules in (("kernel", kernel_rules), ("bias", bias_rules)):
            if kind in names:
                mod = names[names.index(kind) - 1]
                return _sanitize(rules.get(mod, P()), leaf, n_model)
        return P()                              # ln scales/biases

    return jax.tree_util.tree_map_with_path(rule, params)


def lm_cache_specs(cache: Any, *, n_model: int, kv_shard: bool = True) -> Any:
    """PartitionSpec tree for the *stacked* decode cache: slot axis stays
    on the data axis (`P(None, "data")` — dim 1 of every stacked leaf),
    and the KV head dim (dim 3 of `cached_k`/`cached_v` [L, S, T, kvh, hd],
    dim 3 of `k_scale`/`v_scale` [L, S, T, kvh]) shards over "model" when
    the KV heads divide; cursors and everything else ride the data axis
    only."""
    M = MODEL_AXIS if (n_model > 1 and kv_shard) else None

    def rule(path, leaf):
        names = _path_names(path)
        if M and names and names[-1] in ("cached_k", "cached_v"):
            return _sanitize(P(None, DATA_AXIS, None, M, None),
                             leaf, n_model)
        if M and names and names[-1] in ("k_scale", "v_scale"):
            return _sanitize(P(None, DATA_AXIS, None, M), leaf, n_model)
        return P(None, DATA_AXIS) if leaf.ndim >= 2 else P()

    return jax.tree_util.tree_map_with_path(rule, cache)


def shard_lm_params(mesh: Mesh, model: Any, params: Any) -> Any:
    """Device-put an LM param tree onto ``mesh`` with the TP specs,
    stacking flat per-block params first if needed. The committed
    shardings flow into `engine.generate`'s jit unchanged, so `generate`
    runs the IDENTICAL sharded step the serving pool runs — exactness
    across ``n_model`` stays structural. Raises `MeshShapeError` when the
    Q heads can't split over the mesh's model axis."""
    from idunno_tpu.models.transformer import stack_block_params
    from idunno_tpu.parallel.mesh import check_head_divisibility

    n_model = int(mesh.shape.get(MODEL_AXIS, 1))
    if "blocks" not in params and "block0" in params:
        params = stack_block_params(params, model.depth)
    if n_model <= 1:
        return replicate(mesh, params)
    check_head_divisibility(model.num_heads, n_model)
    kvh = getattr(model, "num_kv_heads", None) or model.num_heads
    specs = lm_tp_specs(params, n_model=n_model,
                        kv_shard=kvh % n_model == 0)
    return jax.tree.map(
        lambda leaf, sp: jax.device_put(leaf, NamedSharding(mesh, sp)),
        params, specs)


def tp_collective_bytes(model: Any, slots: int, n_model: int) -> int:
    """Estimated psum payload per decode step: two row-parallel reductions
    per block (attention out + mlp_down), each over a [slots, 1, dim]
    activation. 0 when TP is off — the gauge reads as "bytes moved over
    the model axis per dispatched token step"."""
    if n_model <= 1:
        return 0
    itemsize = jnp.zeros((), model.dtype).dtype.itemsize
    return 2 * model.depth * slots * model.dim * itemsize


def sampling_collective_bytes(model: Any, slots: int, n_model: int) -> int:
    """Estimated merge payload of the vocab-sharded sampling tail per
    decode step (ISSUE 16): with the unembed column-sharded, each pick
    merges per-row SCALAR shard stats (running max, mass sum, argmax
    value+index — 4 f32-sized words per row) instead of all-gathering
    the [slots, vocab] logits. 0 when TP is off or the vocab doesn't
    divide the model axis (the head degrades to replicated and the tail
    runs shard-local)."""
    if n_model <= 1 or model.vocab % n_model:
        return 0
    return 4 * slots * 4


# -- CNN tensor parallelism (pod-slice serving) -----------------------------

def cnn_tp_specs(variables: Any, *, n_model: int,
                 min_features: int = 128) -> Any:
    """PartitionSpec tree for CNN inference variables: shard the last
    (output-features / cout) dim of wide kernels over the model axis,
    replicate biases, norms, and narrow layers (the folded preprocess
    stem's 64-channel conv stays replicated, so `preprocess="auto"`
    folding is untouched). QTensor fields sanitize the same way as LM
    params."""
    def rule(path, leaf):
        if (n_model > 1 and hasattr(leaf, "ndim") and leaf.ndim >= 2
                and leaf.shape[-1] >= min_features
                and leaf.shape[-1] % n_model == 0):
            return P(*([None] * (leaf.ndim - 1) + [MODEL_AXIS]))
        return P()

    return jax.tree_util.tree_map_with_path(rule, variables)


def shard_cnn_variables(mesh: Mesh, variables: Any) -> Any:
    """Device-put CNN variables with `cnn_tp_specs` (replicate when the
    mesh has no model axis extent)."""
    n_model = int(mesh.shape.get(MODEL_AXIS, 1))
    if n_model <= 1:
        return replicate(mesh, variables)
    specs = cnn_tp_specs(variables, n_model=n_model)
    return jax.tree.map(
        lambda leaf, sp: jax.device_put(leaf, NamedSharding(mesh, sp)),
        variables, specs)
