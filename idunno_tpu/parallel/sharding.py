"""Sharding policies.

Params are replicated across the data axis (each chip holds the full model in
HBM — the reference's whole-model-per-worker layout, `alexnet_resnet.py:18-22`,
done right); batches are sharded over the data axis so each chip computes its
contiguous slice of the query range. Optional tensor parallelism shards wide
kernels over the model axis.
"""
from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from idunno_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) dim split over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch: Any) -> Any:
    """Place a host batch on the mesh, leading dim over the data axis."""
    return jax.device_put(batch, batch_sharding(mesh))


def replicate(mesh: Mesh, tree: Any) -> Any:
    """Replicate a pytree (model variables) across the whole mesh."""
    return jax.device_put(tree, replicated_sharding(mesh))


def tp_param_spec(path: tuple, leaf: Any) -> P:
    """Tensor-parallel PartitionSpec for a param leaf: shard the last
    (output-features) dim of large Dense kernels over the model axis,
    replicate everything else. Used by the optional TP engine mode."""
    name = "/".join(str(p) for p in path)
    if leaf.ndim >= 2 and leaf.shape[-1] % 2 == 0 and "fc" in name and leaf.size > 1 << 20:
        return P(*([None] * (leaf.ndim - 1) + [MODEL_AXIS]))
    return P()
