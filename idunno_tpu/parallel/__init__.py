from idunno_tpu.parallel.mesh import make_mesh, local_mesh  # noqa: F401
from idunno_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding, replicated_sharding, shard_batch)
