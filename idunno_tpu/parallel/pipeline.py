"""Pipeline parallelism: GPipe-style microbatch schedule over a stage axis.

The reference never splits a model — each VM holds a whole AlexNet/ResNet
(`alexnet_resnet.py:18-22`); its only decomposition is range sharding of the
query stream (`mp4_machinelearning.py:516-536`). For models that do not fit
one chip the TPU framework adds the missing axis: the layer stack is cut
into ``p`` stages, one per mesh shard along ``STAGE_AXIS``; microbatches
stream through the stages, activations hop stage→stage over ICI via
``ppermute``, and every device runs the same SPMD program (a
``shard_map``-wrapped ``fori_loop`` over the M + p - 1 schedule slots), so
XLA overlaps the hop with the next microbatch's compute.

The schedule is the classic GPipe fill/steady/drain: at slot ``t`` stage
``s`` processes microbatch ``t - s`` (when in range). Bubble fraction is
(p-1)/(M+p-1) — callers pick M >> p. The whole pipeline is differentiable
(plain JAX ops), so the same function serves inference and training.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from idunno_tpu.parallel._compat import pvary as _pvary, shard_map

STAGE_AXIS = "stage"


def stack_stage_params(per_stage: list[Any]) -> Any:
    """Stack p structurally-identical per-stage param pytrees along a new
    leading stage dim (leaf [p, ...]) — the layout ``pipeline_apply`` shards
    over the stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)


def split_microbatches(x: jnp.ndarray, num: int) -> jnp.ndarray:
    """[N, ...] → [num, N/num, ...]."""
    if x.shape[0] % num:
        raise ValueError(f"batch {x.shape[0]} not divisible by {num}")
    return x.reshape(num, x.shape[0] // num, *x.shape[1:])


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stage_params: Any, microbatches: jnp.ndarray,
                   mesh: Mesh, *, axis: str = STAGE_AXIS,
                   data_axis: str | None = None) -> jnp.ndarray:
    """Run microbatches through the p-stage pipeline.

    stage_fn: (one stage's params, activation [mb, ...]) → [mb, ...]
      (activation shape must be stage-invariant, e.g. transformer blocks).
    stage_params: pytree with leaves [p, ...] (see ``stack_stage_params``).
    microbatches: [M, mb, ...] — the global input, replicated.
    Returns [M, mb, ...] — equal to stage_{p-1}(...stage_0(x)), replicated.

    2-D composition: with ``data_axis`` set (a second mesh axis), the
    microbatch dim mb is sharded over it — each data shard runs the same
    GPipe schedule on its slice of every microbatch (PP × DP; stage params
    stay replicated across ``data_axis``, so XLA all-reduces their grads
    over it under AD, the standard DP contract)."""
    p = mesh.shape[axis]
    m = microbatches.shape[0]
    if data_axis is not None:
        dp = mesh.shape[data_axis]
        if microbatches.shape[1] % dp:
            raise ValueError(
                f"microbatch size {microbatches.shape[1]} not divisible by "
                f"data axis {data_axis!r} size {dp}")

    def body(params_sh, x):
        # params_sh leaves arrive [1, ...] (stage-sharded); drop the dim.
        params = jax.tree.map(lambda a: a[0], params_sh)
        s = jax.lax.axis_index(axis)
        perm = [(j, (j + 1) % p) for j in range(p)]
        state0 = _pvary(jnp.zeros_like(x[0]), axis)
        out0 = _pvary(jnp.zeros_like(x), axis)
        xv = _pvary(x, axis)

        def slot(t, carry):
            state, outputs = carry
            feed = xv[jnp.clip(t, 0, m - 1)]
            inp = jnp.where(s == 0, feed, state)
            act = stage_fn(params, inp)
            state_next = jax.lax.ppermute(act, axis, perm)
            # the last stage's activation at slot t is microbatch t-(p-1)
            oidx = jnp.clip(t - (p - 1), 0, m - 1)
            write = jnp.logical_and(s == p - 1, t >= p - 1)
            outputs = jnp.where(write,
                                jax.lax.dynamic_update_index_in_dim(
                                    outputs, act, oidx, 0),
                                outputs)
            return state_next, outputs

        _, outputs = jax.lax.fori_loop(0, m + p - 1, slot, (state0, out0))
        # only stage p-1 holds real outputs; psum replicates them everywhere
        mask = jnp.where(s == p - 1, 1.0, 0.0).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, axis)

    mb_spec = P(None, data_axis) if data_axis else P()
    return shard_map(body, mesh=mesh,
                     in_specs=(jax.tree.map(lambda _: P(axis), stage_params),
                               mb_spec),
                     out_specs=mb_spec)(stage_params, microbatches)
