"""idunno_tpu — a TPU-native distributed ML inference framework.

A from-scratch re-architecture of the capabilities of the IDunno distributed
learning cluster (UIUC CS425 MP4, reference: kentchen831213/
-Distributed-Machine-Learning-System): cluster membership + failure detection,
a replicated versioned file store, fair-time scheduling of concurrent model
jobs, straggler/failed-host task reassignment, standby-coordinator failover,
live stats, and an interactive operations shell — built TPU-first:

- compute path: jit-compiled Flax models resident in HBM, batched bfloat16
  forwards on the MXU, sharded over a `jax.sharding.Mesh` (data parallel over
  the batch axis, optional tensor parallelism), results collected with XLA
  collectives over ICI rather than N-way TCP broadcasts
  (reference: per-image torch forwards, `alexnet_resnet.py:12-92`);
- control plane: typed messages over a pluggable transport (in-process for
  tests, UDP/TCP over DCN between TPU hosts), replacing the reference's
  `"<SEPARATOR>"` string frames (`mp4_machinelearning.py:54`).

Package layout (SURVEY.md §7):
    config      — cluster/runtime configuration (no hardcoded IPs)
    utils       — enums, hash ring, logging taxonomy
    comm        — transports + typed control-plane messages + device mesh
    membership  — join/heartbeat/failure detector
    store       — replicated versioned file store (SDFS verbs)
    models      — Flax AlexNet / ResNet-18
    ops         — preprocessing + device-side classification ops
    engine      — jit-compiled batched inference + training steps
    parallel    — sharding policies, collectives, mesh helpers
    scheduler   — fair-time multi-job scheduling, task bookkeeping
    serve       — node assembly, coordinator/worker, metrics, failover
    cli         — interactive operations shell
    grep        — distributed log grep
"""

__version__ = "0.2.0"

# Lazy top-level API (PEP 562): importing `idunno_tpu` stays light
# (control-plane nodes shouldn't pay for flax/model imports); the common
# surfaces resolve on first use.
_LAZY_API = {
    "InferenceEngine": ("idunno_tpu.engine.inference", "InferenceEngine"),
    "QueryResult": ("idunno_tpu.engine.inference", "QueryResult"),
    "TransformerLM": ("idunno_tpu.models.transformer", "TransformerLM"),
    "MoETransformerLM": ("idunno_tpu.models.moe", "MoETransformerLM"),
    "make_attn_fn": ("idunno_tpu.models.transformer", "make_attn_fn"),
    "generate": ("idunno_tpu.engine.generate", "generate"),
    "make_mesh": ("idunno_tpu.parallel.mesh", "make_mesh"),
    "local_mesh": ("idunno_tpu.parallel.mesh", "local_mesh"),
    "global_mesh": ("idunno_tpu.parallel.mesh", "global_mesh"),
    "initialize_distributed": ("idunno_tpu.parallel.mesh",
                               "initialize_distributed"),
    "Node": ("idunno_tpu.serve.node", "Node"),
    "ClusterConfig": ("idunno_tpu.config", "ClusterConfig"),
    "EngineConfig": ("idunno_tpu.config", "EngineConfig"),
}


def __getattr__(name):
    if name in _LAZY_API:
        import importlib
        mod, attr = _LAZY_API[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module 'idunno_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY_API))
