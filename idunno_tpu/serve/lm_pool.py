"""Thread-owned serving loop around `engine.serve_lm.DecodeServer`.

`DecodeServer` is a single-threaded object (device state + host bookkeeping
mutate together); the cluster runtime needs submissions and polls arriving
from RPC handler threads while a dedicated thread drives the decode loop.
This wrapper gives the server exactly one driving thread and puts a lock
between it and the RPC side: submissions land in a host-side inbox the loop
drains, completions accumulate in a host-side outbox polls swap out.

The loop sleeps on an event while idle (no busy-spin — the reference's
`monitor_query_rate` burns a core, `mp4_machinelearning.py:1016-1036`) and
wakes on submit or stop.
"""
from __future__ import annotations

import threading
from typing import Any

from idunno_tpu.engine.serve_lm import Completion, DecodeServer


class LMServingLoop:
    """One background thread driving one DecodeServer; all public methods
    are safe to call from any thread."""

    def __init__(self, server: DecodeServer, name: str = "lm") -> None:
        self.server = server
        self._lock = threading.Lock()
        # (id, toks, max_new, temperature, top_p, seed)
        self._inbox: list[
            tuple[int, list[int], int, float, float, int | None]] = []
        self._outbox: list[Completion] = []
        self._next_id = 0
        self._id_map: dict[int, int] = {}     # server-side id → public id
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._errors: list[str] = []
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"{name}-decode-loop")
        self._thread.start()

    # -- any thread -------------------------------------------------------

    def submit(self, tokens: list[int], max_new: int, *,
               temperature: float = 0.0, top_p: float = 1.0,
               seed: int | None = None) -> int:
        """Validate + queue a prompt; returns the public request id.
        Raises once the pool is stopped — a submit racing `stop()` must
        error loudly, not return an id that never completes."""
        # validate eagerly on the caller's thread so the RPC gets the error
        # (the loop thread has nowhere to raise to)
        self.server.validate(tokens, max_new, temperature, top_p)
        with self._lock:
            # checked under the lock: stop() sets the flag BEFORE its own
            # locked inbox drain, so an append here either precedes the
            # drain (request errored there) or sees the flag (raises here)
            if self._stop.is_set():
                raise ValueError("serving pool is stopped")
            rid = self._next_id
            self._next_id += 1
            self._inbox.append((rid, list(tokens), max_new,
                                temperature, top_p, seed))
        self._wake.set()
        return rid

    def poll(self) -> list[Completion]:
        """Completions since the last poll (public ids)."""
        with self._lock:
            out, self._outbox = self._outbox, []
            return out

    def stats(self) -> dict:
        """Server counters + this loop's queue depths. The server's dict is
        only mutated by the loop thread; int reads are GIL-atomic."""
        out = self.server.stats()
        with self._lock:
            out["inbox"] = len(self._inbox)
            out["unpolled"] = len(self._outbox)
        return out

    def errors(self) -> list[str]:
        """Errors since the last call (drained, like `poll`)."""
        with self._lock:
            out, self._errors = self._errors, []
            return out

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=timeout)
        with self._lock:          # fail anything the loop never drained
            dropped, self._inbox = self._inbox, []
            for entry in dropped:
                if len(self._errors) < 100:
                    self._errors.append(
                        f"request {entry[0]} dropped: pool stopped")

    # -- loop thread ------------------------------------------------------

    def _drain_inbox(self) -> None:
        with self._lock:
            batch, self._inbox = self._inbox, []
        for rid, tokens, max_new, temperature, top_p, seed in batch:
            sid = self.server.submit(tokens, max_new,
                                     temperature=temperature, top_p=top_p,
                                     seed=rid if seed is None else seed)
            self._id_map[sid] = rid

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._drain_inbox()
                live = self.server.step()
                done = self.server.poll()
            except Exception as e:  # noqa: BLE001 - loop must stay alive
                with self._lock:
                    if len(self._errors) < 100:   # bounded between drains
                        self._errors.append(f"{type(e).__name__}: {e}")
                live, done = 0, []
            if done:
                with self._lock:
                    for c in done:
                        self._outbox.append(Completion(
                            id=self._id_map.pop(c.id, c.id),
                            tokens=c.tokens, prompt_len=c.prompt_len,
                            service_s=c.service_s))
            if live == 0:
                self._wake.wait(timeout=0.5)
                self._wake.clear()
