"""Thread-owned serving loop around `engine.serve_lm.DecodeServer`.

`DecodeServer` is a single-threaded object (device state + host bookkeeping
mutate together); the cluster runtime needs submissions and polls arriving
from RPC handler threads while a dedicated thread drives the decode loop.
This wrapper gives the server exactly one driving thread and puts a lock
between it and the RPC side: submissions land in a host-side inbox the loop
drains, completions accumulate in a host-side outbox polls swap out.

The loop sleeps on an event while idle (no busy-spin — the reference's
`monitor_query_rate` burns a core, `mp4_machinelearning.py:1016-1036`) and
wakes on submit or stop.

With a `serve/gateway.py:AdmissionGateway` attached, submissions go
through admission (quota/backpressure sheds raise on the caller's
thread) into the gateway's priority queues instead of the FIFO inbox;
the loop thread pulls from the gateway with a dispatch budget that keeps
the server-side queue shallow (~2 batches deep), so EDF/fair-queueing
decisions are made as late as possible, and completes expired entries
as ``rejected="expired"`` without ever decoding them.
"""
from __future__ import annotations

import threading

from idunno_tpu.engine.serve_lm import Completion, DecodeServer
from idunno_tpu.serve.admission import PRIORITIES, AdmissionShed
from idunno_tpu.serve.gateway import AdmissionGateway


class LMServingLoop:
    """One background thread driving one DecodeServer; all public methods
    are safe to call from any thread."""

    def __init__(self, server: DecodeServer, name: str = "lm",
                 gateway: AdmissionGateway | None = None,
                 spans=None) -> None:
        self.server = server
        self.gateway = gateway
        # per-node span recorder (utils/spans.SpanStore | None); wiring it
        # here also hands it to the server for prefill/decode-step spans
        self.spans = spans
        if spans is not None:
            server.spans = spans
        # rid → (trace_id, admit_span_id, t_enq) while in flight;
        # rid → trace_id survives completion so the `trace` verb can
        # resolve a finished request's trace (bounded, insertion-ordered)
        self._traces: dict[int, tuple] = {}
        self._trace_ids: dict[int, str] = {}
        self._lock = threading.Lock()
        # (id, toks, max_new, temperature, top_p, top_k, pres, freq,
        #  stop, seed)
        self._inbox: list[tuple] = []
        self._outbox: list[Completion] = []
        self._next_id = 0
        self._id_map: dict[int, int] = {}     # server-side id → public id
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._errors: list[str] = []
        # cancellation + snapshot both mutate/read DecodeServer state, so
        # they are handed to the loop thread: cancels as a drained box,
        # snapshots as a request/response pair of events
        self._cancel_box: list[int] = []      # server-side ids
        self._snap_serial = threading.Lock()  # one snapshot waiter at a time
        self._snap_want = threading.Event()
        self._snap_done = threading.Event()
        self._snap: list[dict] = []
        # cluster prefix-cache ops (publish/probe/fetch) mutate server
        # state, so RPC threads marshal them to the loop thread exactly
        # like snapshots; tenant notes ride a drained box
        self._prefix_serial = threading.Lock()
        self._prefix_want = threading.Event()
        self._prefix_done = threading.Event()
        self._prefix_req: tuple | None = None
        self._prefix_out: object = None
        self._note_box: list[tuple] = []      # (tokens, tenant)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"{name}-decode-loop")
        self._thread.start()

    # -- any thread -------------------------------------------------------

    def submit(self, tokens: list[int], max_new: int, *,
               temperature: float = 0.0, top_p: float = 1.0,
               top_k: int = 0, presence_penalty: float = 0.0,
               frequency_penalty: float = 0.0,
               stop: list[list[int]] | None = None,
               seed: int | None = None,
               tenant: str = "default", priority: str = "interactive",
               deadline_ms: float | None = None,
               readmit: bool = False,
               trace: tuple | None = None) -> int:
        """Validate + queue a prompt; returns the public request id.
        Raises once the pool is stopped — a submit racing `stop()` must
        error loudly, not return an id that never completes.

        On a gateway pool, admission runs here on the caller's thread:
        an `AdmissionShed` (quota / queue_full / backpressure) raises
        before any id is queued. ``readmit=True`` is the manager's replay
        path — an already-admitted request being re-forwarded after node
        death bypasses admission checks (but still queues by class/ft)."""
        # validate eagerly on the caller's thread so the RPC gets the error
        # (the loop thread has nowhere to raise to)
        self.server.validate(tokens, max_new, temperature, top_p, top_k,
                             presence_penalty, frequency_penalty, stop)
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, "
                             f"got {priority!r}")
        tr = tuple(trace) if self.spans is not None and trace else None
        with self._lock:
            # checked under the lock: stop() sets the flag BEFORE its own
            # locked inbox drain, so an append here either precedes the
            # drain (request errored there) or sees the flag (raises here)
            if self._stop.is_set():
                raise ValueError("serving pool is stopped")
            rid = self._next_id
            self._next_id += 1
            entry = (rid, list(tokens), max_new, temperature, top_p, top_k,
                     presence_penalty, frequency_penalty, stop, seed)
            if self.gateway is None:
                self._inbox.append(entry)
        if self.gateway is None:
            if tr is not None:   # outside the lock: _book_trace takes it
                sp = self.spans.record(
                    "lm.admit", trace=tr[0], parent=tr[1],
                    attrs={"rid": rid, "tenant": tenant,
                           "priority": priority, "gateway": False})
                self._book_trace(rid, tr[0], sp.span_id, sp.t_end)
        if self.gateway is not None:
            # outside self._lock: the gateway has its own lock, and a shed
            # must not leave loop state half-mutated (rid gaps are fine)
            t0 = self.spans.clock() if tr is not None else None
            try:
                self.gateway.admit(rid, entry, tenant=tenant,
                                   priority=priority,
                                   deadline_ms=deadline_ms,
                                   pool_gauges=self._pool_gauges(),
                                   readmit=readmit)
            except AdmissionShed as e:
                if tr is not None:   # shed is terminal — trace records it
                    self.spans.record(
                        "lm.shed", trace=tr[0], parent=tr[1], t_start=t0,
                        attrs={"rid": rid, "reason": e.reason,
                               "tenant": tenant, "priority": priority})
                raise
            if tr is not None:
                sp = self.spans.record(
                    "lm.admit", trace=tr[0], parent=tr[1], t_start=t0,
                    attrs={"rid": rid, "tenant": tenant,
                           "priority": priority, "gateway": True,
                           "readmit": bool(readmit)})
                self._book_trace(rid, tr[0], sp.span_id, sp.t_end)
            # a stop() racing in between admit and here has already drained
            # the gateway; pull our entry back out and error like any other
            # post-stop submit (cancel() returning None = stop drained it,
            # in which case it was errored there)
            if self._stop.is_set() and self.gateway.cancel(rid) is not None:
                raise ValueError("serving pool is stopped")
        # tenant attribution for cluster prefix publishes (no-op when
        # the cluster tier is off)
        self.note_tenant(tokens, tenant)
        self._wake.set()
        return rid

    def _book_trace(self, rid: int, tid: str, sid: str,
                    t_enq: float) -> None:
        """Remember an admitted request's trace: in-flight tuple for the
        queue-wait/finish spans, plus the rid → trace_id map the `trace`
        verb resolves after completion (bounded FIFO)."""
        with self._lock:
            self._traces[rid] = (tid, sid, t_enq)
            self._trace_ids[rid] = tid
            while len(self._trace_ids) > 4096:
                self._trace_ids.pop(next(iter(self._trace_ids)))

    def _trace_done(self, rid: int, name: str, **attrs) -> None:
        """Record the terminal span (finish/cancel/expire) for ``rid`` and
        retire its in-flight trace entry."""
        tr = self._traces.pop(rid, None)
        if tr is not None and self.spans is not None:
            self.spans.record(name, trace=tr[0], parent=tr[1],
                              attrs={"rid": rid, **attrs})

    def trace_of(self, rid: int) -> str | None:
        """Trace id of a public request id (live or recently finished);
        None for untraced/unknown ids."""
        with self._lock:
            return self._trace_ids.get(rid)

    def _pool_gauges(self) -> dict:
        """Live occupancy snapshot for backpressure. Reads of the server's
        containers from RPC threads are GIL-atomic len()s; the gateway adds
        its own queue depth to ``waiting`` under its lock."""
        srv = self.server
        g = {"waiting": len(self._inbox) + len(srv._queue),
             "live": len(srv._live), "slots": srv.slots}
        bp = srv._block_pool
        if bp is not None:
            g["kv_blocks_free"] = bp.num_free
            g["kv_blocks_total"] = bp.num_blocks
        return g

    def poll(self) -> list[Completion]:
        """Completions since the last poll (public ids)."""
        with self._lock:
            out, self._outbox = self._outbox, []
            return out

    def cancel(self, rid: int) -> bool:
        """Best-effort cancel of public request ``rid``. A request still in
        the inbox is dropped here and completes (cancelled, prompt-only)
        immediately; one already on the server is cancelled by the loop
        thread at its next iteration and completes with whatever tokens it
        had. Returns False when the id is unknown — already completed (its
        tokens are in the outbox or were polled) or never submitted."""
        if self.gateway is not None:
            e = self.gateway.cancel(rid)
            if e is not None:
                full = (self.server.prefix or []) + list(e.payload[1])
                with self._lock:
                    self._outbox.append(Completion(
                        id=rid, tokens=full,
                        prompt_len=len(full), cancelled=True,
                        logprobs=([] if self.server.track_logprobs
                                  else None)))
                self._trace_done(rid, "lm.cancel", where="gateway")
                return True
        with self._lock:
            for i, entry in enumerate(self._inbox):
                if entry[0] == rid:
                    del self._inbox[i]
                    full = (self.server.prefix or []) + list(entry[1])
                    self._outbox.append(Completion(
                        id=rid, tokens=full,
                        prompt_len=len(full), cancelled=True,
                        logprobs=([] if self.server.track_logprobs
                                  else None)))
                    self._trace_done(rid, "lm.cancel", where="inbox")
                    return True
            sid = next((s for s, r in self._id_map.items() if r == rid),
                       None)
            if sid is None:
                return False
            self._cancel_box.append(sid)
        self._wake.set()
        return True

    def prefix_op(self, op: str, timeout: float = 30.0, **kw) -> dict:
        """Run a cluster prefix-cache operation ("publish" | "probe" |
        "fetch") on the LOOP thread — the DecodeServer's radix tree and
        block pool are loop-thread-owned, so RPC handlers must marshal
        (same request/response-event shape as `snapshot`). Raises the
        op's error on this thread; ValueError on timeout."""
        if self.server.cluster_prefix is None:
            raise ValueError("pool has no cluster prefix cache "
                             "(serve with cluster_prefix=)")
        with self._prefix_serial:
            self._prefix_done.clear()
            self._prefix_req = (op, kw)
            self._prefix_want.set()
            self._wake.set()
            if not self._prefix_done.wait(timeout):
                self._prefix_want.clear()
                self._prefix_req = None
                raise ValueError(f"prefix_{op} timed out after "
                                 f"{timeout}s")
            out = self._prefix_out
        if isinstance(out, Exception):
            raise ValueError(f"prefix_{op}: {out}") from out
        return out

    def handoff_op(self, op: str, timeout: float = 30.0, **kw) -> dict:
        """Run a DistServe KV-handoff operation ("probe" | "export" |
        "adopt" | "fallback") on the LOOP thread — handoff export/adopt
        walk the radix tree and block pool, which are loop-thread-owned,
        so RPC handlers marshal exactly like `prefix_op` (the two op
        families share the serialized request/response channel). Gated
        on the block tier, NOT the cluster prefix cache: a handoff is
        point-to-point and needs no SDFS ring."""
        if self.server._radix is None:
            raise ValueError("pool has no KV block tier "
                             "(serve with kv_block_size > 0)")
        with self._prefix_serial:
            self._prefix_done.clear()
            self._prefix_req = (f"handoff_{op}", kw)
            self._prefix_want.set()
            self._wake.set()
            if not self._prefix_done.wait(timeout):
                self._prefix_want.clear()
                self._prefix_req = None
                raise ValueError(f"kv_handoff {op} timed out after "
                                 f"{timeout}s")
            out = self._prefix_out
        if isinstance(out, Exception):
            raise ValueError(f"kv_handoff {op}: {out}") from out
        return out

    def note_tenant(self, tokens: list[int], tenant: str) -> None:
        """Record (prompt head → tenant) for publish attribution; the
        loop thread drains the box into the cluster cache."""
        if self.server.cluster_prefix is None:
            return
        with self._lock:
            self._note_box.append((list(tokens), str(tenant)))

    def snapshot(self, timeout: float = 2.0) -> list[dict]:
        """Progress of every live row (public ids): prompt + tokens
        generated so far — the streaming surface behind ``lm_partial``.
        Fulfilled by the loop thread at its next iteration; returns [] if
        the loop doesn't answer within ``timeout`` (stopped or wedged)."""
        with self._snap_serial:
            self._snap_done.clear()
            self._snap_want.set()
            self._wake.set()
            if not self._snap_done.wait(timeout):
                self._snap_want.clear()
                return []
            with self._lock:
                return list(self._snap)

    def stats(self) -> dict:
        """Server counters + this loop's queue depths. The server's dict is
        only mutated by the loop thread; int reads are GIL-atomic."""
        out = self.server.stats()
        with self._lock:
            out["inbox"] = len(self._inbox)
            out["unpolled"] = len(self._outbox)
        if self.gateway is not None:
            out["gateway"] = self.gateway.stats()
        return out

    def errors(self) -> list[str]:
        """Errors since the last call (drained, like `poll`)."""
        with self._lock:
            out, self._errors = self._errors, []
            return out

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=timeout)
        with self._lock:          # fail anything the loop never drained
            dropped, self._inbox = self._inbox, []
            if self.gateway is not None:
                dropped = dropped + [e.payload for e in self.gateway.drain()]
            for entry in dropped:
                self._traces.pop(entry[0], None)
                if len(self._errors) < 100:
                    self._errors.append(
                        f"request {entry[0]} dropped: pool stopped")

    # -- loop thread ------------------------------------------------------

    def _drain_inbox(self) -> None:
        with self._lock:
            batch, self._inbox = self._inbox, []
        for (rid, tokens, max_new, temperature, top_p, top_k, pres,
             freq, stop, seed) in batch:
            ctx = self._queue_wait_span(rid)
            sid = self.server.submit(tokens, max_new,
                                     temperature=temperature, top_p=top_p,
                                     top_k=top_k, presence_penalty=pres,
                                     frequency_penalty=freq, stop=stop,
                                     seed=rid if seed is None else seed,
                                     trace=ctx)
            # under the lock: cancel() iterates this map from RPC threads
            with self._lock:
                self._id_map[sid] = rid

    def _queue_wait_span(self, rid: int,
                         t_enq: float | None = None) -> tuple | None:
        """Record the queue-wait span for ``rid`` (admission → dispatch to
        the server) and return the (trace_id, admit_span_id) context the
        server's prefill span chains under; None when untraced.
        ``t_enq`` overrides the booked enqueue time (the gateway entry's
        own timestamp — same clock in fake-clock tests)."""
        tr = self._traces.get(rid)
        if tr is None or self.spans is None:
            return None
        self.spans.record(
            "lm.queue_wait", trace=tr[0], parent=tr[1],
            t_start=tr[2] if t_enq is None else float(t_enq),
            attrs={"rid": rid})
        return tr[0], tr[1]

    def _drain_gateway(self) -> None:
        """Pull admitted work from the gateway under a dispatch budget
        that keeps the server queue ~2 batches deep (dispatching later
        keeps EDF/expiry decisions informed by the freshest deadlines),
        and retire expired entries as rejected completions."""
        if self.gateway is None:
            return
        budget = max(0, 2 * self.server.slots - self.server.pending())
        ready, expired = self.gateway.take(budget)
        for e in expired:
            full = (self.server.prefix or []) + list(e.payload[1])
            with self._lock:
                self._outbox.append(Completion(
                    id=e.rid, tokens=full, prompt_len=len(full),
                    rejected="expired",
                    logprobs=([] if self.server.track_logprobs else None)))
            self._trace_done(e.rid, "lm.expire", reason="expired")
        for e in ready:
            (rid, tokens, max_new, temperature, top_p, top_k, pres,
             freq, stop, seed) = e.payload
            ctx = self._queue_wait_span(rid, t_enq=e.t_enq)
            sid = self.server.submit(tokens, max_new,
                                     temperature=temperature, top_p=top_p,
                                     top_k=top_k, presence_penalty=pres,
                                     frequency_penalty=freq, stop=stop,
                                     seed=rid if seed is None else seed,
                                     trace=ctx)
            with self._lock:
                self._id_map[sid] = rid

    def _drain_cancels(self) -> None:
        with self._lock:
            batch, self._cancel_box = self._cancel_box, []
        for sid in batch:
            self.server.cancel(sid)

    def _fulfill_prefix(self) -> None:
        if not self._prefix_want.is_set():
            return
        req = self._prefix_req
        if req is None:                 # waiter timed out and withdrew
            self._prefix_want.clear()
            return
        op, kw = req
        try:
            if op == "publish":
                out: object = self.server.prefix_publish(**kw)
            elif op == "probe":
                out = self.server.prefix_probe(**kw)
            elif op == "fetch":
                out = self.server.prefix_warm(**kw)
            elif op == "handoff_probe":
                out = self.server.handoff_probe(**kw)
            elif op == "handoff_export":
                out = self.server.handoff_export(**kw)
            elif op == "handoff_adopt":
                out = self.server.handoff_adopt(**kw)
            elif op == "handoff_fallback":
                out = self.server.handoff_fallback(**kw)
            else:
                out = ValueError(f"unknown prefix op {op!r}")
        except Exception as e:  # noqa: BLE001 - waiter must not hang
            out = e
        self._prefix_req = None
        self._prefix_out = out
        self._prefix_want.clear()
        self._prefix_done.set()

    def _drain_notes(self) -> None:
        cp = self.server.cluster_prefix
        if cp is None:
            return
        with self._lock:
            batch, self._note_box = self._note_box, []
        for tokens, tenant in batch:
            cp.note(tokens, tenant)

    def _fulfill_snapshot(self) -> None:
        if not self._snap_want.is_set():
            return
        try:
            snap = self.server.snapshot()
        except Exception as e:  # noqa: BLE001 - waiter must not hang
            snap = []
            with self._lock:
                if len(self._errors) < 100:
                    self._errors.append(f"snapshot: {type(e).__name__}: {e}")
        with self._lock:
            rows = []
            for e in snap:
                rid = self._id_map.get(e["id"], e["id"])
                tr = self._traces.get(rid)
                tid = tr[0] if tr else self._trace_ids.get(rid)
                # untraced rows gain no `trace` key — the streaming
                # surface predates tracing and clients diff it exactly
                rows.append(dict(e, id=rid, **({"trace": tid} if tid
                                               else {})))
            self._snap = rows
        self._snap_want.clear()
        self._snap_done.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._drain_cancels()
                self._drain_notes()
                self._drain_inbox()
                self._drain_gateway()
                live = self.server.step()
                done = self.server.poll()
            except Exception as e:  # noqa: BLE001 - loop must stay alive
                with self._lock:
                    if len(self._errors) < 100:   # bounded between drains
                        self._errors.append(f"{type(e).__name__}: {e}")
                live, done = 0, []
            self._fulfill_prefix()
            self._fulfill_snapshot()
            if done:
                with self._lock:
                    for c in done:
                        rid = self._id_map.pop(c.id, c.id)
                        self._outbox.append(Completion(
                            id=rid,
                            tokens=c.tokens, prompt_len=c.prompt_len,
                            service_s=c.service_s, cancelled=c.cancelled,
                            logprobs=c.logprobs,
                            cold_start=c.cold_start))
                        self._trace_done(
                            rid,
                            "lm.cancel" if c.cancelled else "lm.finish",
                            tokens=len(c.tokens))
            if live == 0:
                self._wake.wait(timeout=0.5)
                self._wake.clear()
