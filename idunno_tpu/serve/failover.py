"""Standby-coordinator failover (SURVEY.md C10).

Reference: the master streams its scheduler state, stringified, to the other
nine VMs once a second (`send_metadata`, `mp4_machinelearning.py:971-987`);
every host runs `receive_metadata` (`:989-1011`) — which assigns raw strings
over dict-typed fields, corrupting the very state it exists to preserve
(SURVEY.md §7 bugs-not-to-replicate). Clients fail over primary→standby
(`:956-963`).

Here the acting master replicates a *versioned, typed* snapshot (task book,
per-model query counters, metrics windows, accumulated results) to the
standby each period. When the standby observes the coordinator's death (via
its own ping-silence monitor) it adopts the newest snapshot, reassigns every
in-flight task stranded on dead hosts, and re-dispatches — resuming
unfinished query ranges instead of losing them. Workers already deliver
results master-then-standby, so results in flight during the switch land on
the new master.
"""
from __future__ import annotations

import json
import logging
import threading
from typing import Any

from idunno_tpu.comm.message import Message
from idunno_tpu.comm.transport import Transport, TransportError
from idunno_tpu.config import ClusterConfig
from idunno_tpu.membership.epoch import (check_payload, check_scoped,
                                         place_scope, pool_scope,
                                         reply_is_stale, stamp_scoped)
from idunno_tpu.membership.service import MembershipService
from idunno_tpu.serve.inference_service import InferenceService
from idunno_tpu.utils.types import MemberStatus, MessageType

SERVICE = "metadata"

log = logging.getLogger("idunno.failover")


class FailoverManager:
    def __init__(self, host: str, config: ClusterConfig,
                 transport: Transport, membership: MembershipService,
                 service: InferenceService, lm_manager=None) -> None:
        self.host = host
        self.config = config
        self.transport = transport
        self.membership = membership
        self.service = service
        self.lm_manager = lm_manager    # serve/lm_manager.LMPoolManager
        self._lock = threading.RLock()
        self._seq = 0
        self._received: dict[str, Any] | None = None
        self._received_seq = -1
        # satellite observability: acked queries whose write-ahead was
        # skipped because the standby was down (durability gap until the
        # periodic snapshot catches up) — also a metrics counter
        self.wal_skips = 0
        # standby-side per-query write-ahead deltas, (model, qnum) →
        # {"tasks": [...wire...], "dataset": ...}; applied on adopt for
        # queries the newest full snapshot predates, pruned as snapshots
        # catch up (wal_append / _handle / adopt)
        self._wal: dict[tuple[str, int], dict[str, Any]] = {}
        # standby-side autoscaler scaling deltas, group → {"decision",
        # "entry"} (entry = the group's full wire state at decision
        # time, newest kept); applied on adopt for scaling actions the
        # newest snapshot predates (wal_scale / _handle / adopt)
        self._scale_wal: dict[str, dict[str, Any]] = {}
        # standby-side per-pool journal deltas, pool → {"entry"} (the
        # pool's full wire state at its per-pool wal_seq, newest kept):
        # each managed pool's journal segment replicates independently,
        # so adopting one pool's scope replays only that pool's WAL
        # (wal_pool / _handle / adopt)
        self._pool_wal: dict[str, dict[str, Any]] = {}
        # satellite observability: bytes shipped over the pool WAL (full
        # entries + delta frames) — the delta-compaction win is this
        # gauge staying near-linear in mutations instead of quadratic
        # in journal depth (metrics_export: pool_wal_bytes)
        self._pool_wal_bytes = 0
        transport.serve(SERVICE, self._handle)
        # front: the adoption (epoch mint) must land BEFORE reassignment
        # callbacks start re-dispatching, so nothing dispatches under the
        # dead owner's epoch during the promotion itself
        membership.on_change(self._on_member_change, front=True)

    # -- master side: periodic replication --------------------------------

    def snapshot(self) -> dict[str, Any]:
        # self._lock: seq order must match state order — two interleaved
        # builders could otherwise deliver a STALE snapshot under a
        # HIGHER seq and the standby would keep it
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict[str, Any]:
        svc = self.service
        with svc._results_lock:
            results = {f"{m}\x00{q}": [list(r) for r in v]
                       for (m, q), v in svc._results.items()}
            qnum = dict(svc._qnum)
        self._seq += 1
        snap = {"seq": self._seq,
                "epoch": list(self.membership.epoch.view()),
                "tasks": svc.scheduler.book.to_wire(),
                "qnum": qnum,
                "idem": svc.idem_to_wire(),
                "metrics": svc.metrics.to_wire(),
                "results": results}
        if self.lm_manager is not None:
            # LM pool registry + request journal ride the same snapshot,
            # so decode pools and train jobs survive a coordinator death
            # exactly like the CNN task book (round-2 VERDICT item 3)
            snap["lm"] = self.lm_manager.to_wire()
        return snap

    def replicate_once(self) -> bool:
        """Acting master → standby; returns True if delivered."""
        if not self.membership.is_acting_master:
            return False
        standby = self.config.standby_coordinator
        if standby == self.host:
            return False
        msg = Message(MessageType.METADATA, self.host, self.snapshot())
        try:
            out = self.transport.call(standby, SERVICE, msg, timeout=10.0)
        except TransportError:
            return False
        if reply_is_stale(self.membership.epoch, out):
            # the standby has seen a higher epoch: we are deposed — the
            # observe above demoted us, stop replicating stale state
            return False
        return out is not None

    def wal_append(self, model: str, qnum: int, tasks, dataset,
                   idem: str | None = None) -> bool:
        """Synchronous per-query write-ahead for the submit path: a query
        the master has ACKed must survive an immediate coordinator death,
        not just one that lands after the next periodic tick. Ships ONLY
        the new query's task bookings (a few hundred bytes — the full
        snapshot grows with cluster lifetime and belongs on the periodic
        loop, not inside every client ack), on a short timeout so an
        alive-but-degraded standby bounds ack latency. Skips (False) when
        the standby is not currently ALIVE — a dead standby must not add
        its timeout to every ack; the periodic loop resumes replication
        when it returns — but the skip is *observable* (log + metrics
        counter), not silent: each one is an acked query that would be
        lost if this master died before the next snapshot."""
        standby = self.config.standby_coordinator
        if standby == self.host or not self.membership.is_acting_master:
            return False
        if standby not in self.membership.members.alive_hosts():
            self.wal_skips += 1
            self.service.metrics.record_counter("wal_skipped_standby_down")
            log.warning("wal_append skipped for %s q%d: standby %s not "
                        "alive (%d skips — acked queries unprotected until "
                        "the next snapshot)", model, qnum, standby,
                        self.wal_skips)
            return False
        msg = Message(MessageType.METADATA, self.host,
                      {"epoch": list(self.membership.epoch.view()),
                       "wal": {"model": model, "qnum": int(qnum),
                               "tasks": [t.to_wire() for t in tasks],
                               "dataset": dataset, "idem": idem}})
        try:
            out = self.transport.call(standby, SERVICE, msg, timeout=2.0)
        except TransportError:
            return False
        if reply_is_stale(self.membership.epoch, out):
            return False
        return out is not None

    def _scope_standby(self, scope: str) -> str | None:
        """The scope's OWN WAL successor (ISSUE 15): the next host in the
        scope's rendezvous placement order after this one, over the alive
        set. Every pool's journal fans out to its own standby instead of
        one global standby — so one host's death leaves every other
        scope's (owner, standby) pair serving untouched, and the adopter
        a death selects is exactly the host already holding the WAL
        (same formula, same liveness view)."""
        alive = set(self.membership.members.alive_hosts())
        return place_scope(scope, self.config.hosts,
                           alive - {self.host})

    def pool_wal_bytes(self) -> int:
        with self._lock:
            return self._pool_wal_bytes

    def wal_scale(self, group: str, decision: dict[str, Any],
                  entry: dict[str, Any]) -> bool:
        """Synchronous write-ahead for an autoscaler scaling decision
        (serve/lm_manager.py:_replicate_scale): a spawn/retire/rebalance
        the group's owner just journaled must survive an immediate
        death, not just one after the next periodic tick — otherwise the
        adopter would re-derive scaling state from gauges instead of
        REPLAYING it (the chaos exact-replay invariant). Ships the
        group's full wire entry (small: routing maps + a bounded
        decision log — replica request journals ride the pool WAL as
        usual) to the GROUP SCOPE's own standby successor; gated on
        holding the journal (the manager only replicates groups it
        owns), not on cluster mastership — scope owners need not be the
        acting master (ISSUE 15). Same skip discipline as wal_append: a
        dead standby must not stall the control loop, but the skip is
        counted, never silent."""
        scope = pool_scope(group)
        standby = self._scope_standby(scope)
        if standby is None or standby == self.host:
            self.wal_skips += 1
            self.service.metrics.record_counter("wal_skipped_standby_down")
            log.warning("wal_scale skipped for group %s seq %s: no alive "
                        "scope standby", group, decision.get("seq"))
            return False
        payload = {"epoch": list(self.membership.epoch.view()),
                   "scale_wal": {"group": str(group),
                                 "decision": dict(decision),
                                 "entry": dict(entry)}}
        stamp_scoped(self.membership.scopes, scope, payload)
        msg = Message(MessageType.METADATA, self.host, payload)
        try:
            out = self.transport.call(standby, SERVICE, msg, timeout=2.0)
        except TransportError:
            return False
        if out is None or reply_is_stale(self.membership.epoch, out):
            return False
        return out.type is not MessageType.ERROR

    def wal_pool(self, name: str,
                 frame: dict[str, Any]) -> dict[str, Any] | None:
        """Synchronous write-ahead for ONE managed pool's journal segment
        (serve/lm_manager.py:_replicate_pool): ships the pool's wire
        entry — or a delta frame since the standby's acked base — at its
        per-pool monotone ``wal_seq`` so an admission or terminal
        transition the pool's owner just journaled survives an immediate
        death without waiting for the periodic full snapshot — and so
        scoped adoption can replay exactly this pool's segment while
        other pools' state is untouched. DistServe handoff edges
        (ISSUE 18) ride these same frames on BOTH endpoints: the decode
        pool's ``req["handoff"]`` state machine (prefilling → shipping →
        adopted | fallback) rides its request rows, and the prefill
        pool's ``handoffs`` ledger rides the scalar ``fields`` of a
        delta — so an adopter that replays a pool WAL sees any
        non-terminal handoff and re-ships or falls back
        (serve/lm_manager.py:_handoff_ship), never loses the request.
        The target is the POOL SCOPE's
        own standby successor, and the gate is holding the journal (the
        manager only replicates pools it owns), not cluster mastership
        (ISSUE 15). Returns the standby's ACK payload (which may carry
        ``need_full`` when a delta frame missed its base) or None when
        skipped/unreachable/fenced — the caller treats None as an unacked
        chain and re-seeds with a full entry next mutation. Same skip
        discipline as wal_append: a dead standby never stalls the
        serving path, but every skip is counted, never silent."""
        scope = pool_scope(name)
        standby = self._scope_standby(scope)
        if standby is None or standby == self.host:
            self.wal_skips += 1
            self.service.metrics.record_counter("wal_skipped_standby_down")
            log.warning("wal_pool skipped for pool %s seq %s: no alive "
                        "scope standby", name, frame.get("wal_seq"))
            return None
        payload = {"epoch": list(self.membership.epoch.view()),
                   "pool_wal": {"name": str(name),
                                "entry": dict(frame)}}
        stamp_scoped(self.membership.scopes, scope, payload)
        msg = Message(MessageType.METADATA, self.host, payload)
        with self._lock:
            self._pool_wal_bytes += len(
                json.dumps(frame, separators=(",", ":"),
                           default=str).encode())
        try:
            out = self.transport.call(standby, SERVICE, msg, timeout=2.0)
        except TransportError:
            return None
        if out is None or reply_is_stale(self.membership.epoch, out):
            return None
        if out.type is MessageType.ERROR:
            return None
        return dict(out.payload or {})

    # -- standby side ------------------------------------------------------

    def _handle(self, service: str, msg: Message) -> Message | None:
        if msg.type is not MessageType.METADATA:
            return None
        # epoch fence: a deposed master's replication must not overwrite
        # the adopted state it diverged from (its seq counter may be
        # HIGHER than ours — seq orders snapshots within one epoch only)
        stale = check_payload(self.membership.epoch, msg.payload, self.host)
        if stale is not None:
            return stale
        # per-scope fence: a deposed POOL owner's WAL frames are refused
        # for that scope only (the scope's adopter minted a higher scope
        # epoch; the cluster fence above may not have moved at all)
        stale = check_scoped(self.membership.scopes, msg.payload, self.host)
        if stale is not None:
            return stale
        with self._lock:
            if "wal" in msg.payload:        # per-query write-ahead delta
                d = msg.payload["wal"]
                self._wal[(d["model"], int(d["qnum"]))] = d
                return Message(MessageType.ACK, self.host)
            if "scale_wal" in msg.payload:  # autoscaler decision delta
                d = msg.payload["scale_wal"]
                cur = self._scale_wal.get(d["group"])
                if (cur is None
                        or int(cur["decision"].get("seq", -1))
                        <= int(d["decision"].get("seq", -1))):
                    self._scale_wal[d["group"]] = d
                return Message(MessageType.ACK, self.host)
            if "pool_wal" in msg.payload:   # per-pool journal delta
                d = msg.payload["pool_wal"]
                frame = d["entry"]
                cur = self._pool_wal.get(d["name"])
                if frame.get("delta"):
                    held = cur["entry"] if cur else None
                    merged = self._merge_pool_delta_locked(held, frame)
                    if merged is None:
                        # gap: NACK so the sender re-ships a full entry
                        return Message(MessageType.ACK, self.host,
                                       {"need_full": True})
                    self._pool_wal[d["name"]] = {"name": d["name"],
                                                 "entry": merged}
                elif (cur is None
                        or int(cur["entry"].get("wal_seq", -1))
                        <= int(frame.get("wal_seq", -1))):
                    self._pool_wal[d["name"]] = d
                return Message(MessageType.ACK, self.host)
            seq = int(msg.payload.get("seq", 0))
            if seq > self._received_seq:
                self._received = msg.payload
                self._received_seq = seq
                # deltas the snapshot has caught up with are durable in it
                have = {(t["model"], int(t["qnum"]))
                        for t in msg.payload.get("tasks", [])}
                self._wal = {k: v for k, v in self._wal.items()
                             if k not in have}
                groups = (msg.payload.get("lm") or {}).get("groups", {})
                self._scale_wal = {
                    g: v for g, v in self._scale_wal.items()
                    if int((groups.get(g) or {}).get("next_seq", -1))
                    < int(v["decision"].get("seq", -1)) + 1}
                pools = (msg.payload.get("lm") or {}).get("pools", {})
                self._pool_wal = {
                    n: v for n, v in self._pool_wal.items()
                    if int((pools.get(n) or {}).get("wal_seq", -1))
                    < int(v["entry"].get("wal_seq", -1))}
        return Message(MessageType.ACK, self.host)

    @staticmethod
    def _merge_pool_delta_locked(held: dict[str, Any] | None,
                                 frame: dict[str, Any]) \
            -> dict[str, Any] | None:
        """Apply a delta frame onto the held full entry. A frame applies
        only when its ``base_seq`` equals the held entry's wal_seq
        EXACTLY — any gap (no held entry, a lost frame, a standby that
        restarted) returns None and the ACK carries ``need_full``, so the
        sender re-ships the full entry. The standby therefore always
        holds FULL merged entries: adoption-time replay
        (``apply_pool_wal``) never sees a frame."""
        if held is None or held.get("delta") \
                or int(held.get("wal_seq", -1)) \
                != int(frame.get("base_seq", -2)):
            return None
        merged = dict(held)
        merged.update(frame.get("fields", {}))
        if "idem" in frame:
            merged["idem"] = dict(frame["idem"])
        reqs = dict(held.get("requests", {}))
        for rid, req in frame.get("changed", {}).items():
            reqs[rid] = req
        for rid in frame.get("removed", ()):
            reqs.pop(rid, None)
        merged["requests"] = reqs
        merged["wal_seq"] = int(frame["wal_seq"])
        return merged

    def _on_member_change(self, host: str, old: MemberStatus | None,
                          new: MemberStatus) -> None:
        if new is not MemberStatus.LEAVE:
            return
        # scope-scoped adoption FIRST (ISSUE 15): ANY host's death makes
        # each survivor adopt exactly the dead host's pool scopes that
        # place on it — cluster mastership may not move at all
        self._adopt_scopes_of(host)
        # then cluster adoption: when the CURRENT master (fence owner once
        # one exists, the configured coordinator before any mint) is the
        # dead host and this node is next in the chain
        owner = self.membership.epoch.owner() or self.config.coordinator
        if host == owner and self.membership.acting_master() == self.host:
            self.adopt()

    def _adopt_scopes_of(self, dead: str) -> None:
        """Adopt the pool scopes the dead host owned (gossiped claims)
        whose rendezvous placement over the survivors lands here: replay
        exactly those scopes' WAL segments, mint their scope fences (the
        dead owner's stamps are refused per pool from here on), and
        claim ownership so routing converges. Every OTHER owner's scopes
        are untouched — the blast radius of one death is exactly its own
        scopes (ISSUE 15)."""
        owners = getattr(self.membership, "owners", None)
        mgr = self.lm_manager
        if owners is None or mgr is None:
            return
        alive = set(self.membership.members.alive_hosts()) - {dead}
        # quorum gate: an isolated minority falsely suspects the WHOLE
        # majority — if it adopted their scopes it would mint claims and
        # scope fences that win the merge at heal, deposing the rightful
        # owners. A node may adopt a dead owner's scopes only while it
        # sees a strict majority of the configured registry alive; a
        # minority successor stays put (unavailable, never split-brained)
        if 2 * len(alive | {self.host}) <= len(self.config.hosts):
            return
        # NOTE: adoption placement is deliberately quarantine-BLIND.
        # Every surviving host evaluates this formula independently, so
        # its inputs must converge fast; health verdicts are per-host
        # views with long divergence windows — feeding them in lets two
        # hosts each compute themselves successor (per-pool split
        # brain). Quarantine steers single-decider placement (the acting
        # master's lm_serve assignment) and routing only.
        scopes = [s for s in owners.owned_by(dead)
                  if place_scope(s, self.config.hosts, alive) == self.host]
        if not scopes:
            return
        want = set(scopes)
        with self._lock:
            pool_wal = {n: dict(d) for n, d in self._pool_wal.items()
                        if pool_scope(n) in want}
            scale_wal = {g: dict(d) for g, d in self._scale_wal.items()
                         if pool_scope(g) in want}
        svc = self.service
        if pool_wal:
            replayed = mgr.apply_pool_wal(pool_wal)
            if replayed:
                svc.metrics.record_counter("pool_wal_replayed", replayed)
        if scale_wal:
            mgr.apply_scale_wal(scale_wal)
        for scope in scopes:
            self.membership.scopes.fence(scope).mint(self.host)
            svc.metrics.record_counter("pool_scope_adopted")
            owners.claim(scope, self.host)
            svc.metrics.record_counter("scope_owner_moves")
        log.info("%s adopted %d pool scope(s) of dead owner %s: %s",
                 self.host, len(scopes), dead, scopes)
        mgr.on_adopt()

    def adopt(self) -> None:
        """Become the coordinator: mint a strictly higher epoch (fencing
        the deposed master everywhere its stamps are checked), load the
        newest replicated snapshot, apply any write-ahead deltas it
        predates, and resume every unfinished range."""
        fence = self.membership.epoch
        with self._lock:
            if fence.owner() == self.host:
                return          # already own the current epoch
            snap = self._received
            wal = dict(self._wal)
            scale_wal = {g: dict(d) for g, d in self._scale_wal.items()}
            pool_wal = {n: dict(d) for n, d in self._pool_wal.items()}
        # the snapshot carries the deposed master's epoch: fold it into
        # the high-water mark FIRST so the mint lands strictly above
        # everything that master ever stamped
        ep = snap.get("epoch") if snap is not None else None
        if ep:
            fence.observe(int(ep[0]), ep[1])
        epoch = fence.mint(self.host)
        log.info("%s adopting mastership at epoch %d (snapshot seq %s, "
                 "%d wal deltas)", self.host, epoch,
                 snap.get("seq") if snap else None, len(wal))
        svc = self.service
        asp = None
        if svc.spans is not None:
            # the adoption is itself a span — in its OWN trace (the event
            # is cluster-scoped, not owned by any one request), finished
            # after resume_in_flight so its duration covers the promotion
            asp = svc.spans.start(
                "failover.adopt",
                attrs={"epoch": epoch,
                       "snapshot_seq": snap.get("seq") if snap else None,
                       "wal_deltas": len(wal)})
        if snap is not None:
            svc.scheduler.book.load_wire(snap["tasks"])
            with svc._results_lock:
                svc._qnum.update({m: max(int(q), svc._qnum.get(m, 0))
                                  for m, q in snap["qnum"].items()})
            svc.metrics.load_wire(snap["metrics"])
            svc.idem_load_wire(snap.get("idem", {}))
            with svc._results_lock:
                for key, recs in snap["results"].items():
                    m, q = key.split("\x00")
                    existing = svc._results.setdefault((m, int(q)), [])
                    seen = {tuple(r) for r in existing}
                    existing.extend(tuple(r) for r in recs
                                    if tuple(r) not in seen)
        # write-ahead deltas: queries ACKed after the newest snapshot was
        # built (possibly before ANY snapshot ran) — re-book their task
        # assignments so resume_in_flight re-dispatches them
        from idunno_tpu.scheduler.tasks import Task
        for (m, q), d in sorted(wal.items()):
            if not svc.scheduler.book.tasks_for_query(m, q):
                svc.scheduler.book.record(
                    [Task.from_wire(t) for t in d["tasks"]])
            with svc._results_lock:
                svc._qnum[m] = max(svc._qnum.get(m, 0), int(q))
            if d.get("idem"):
                # a client retrying its acked submit against the NEW
                # master must dedupe, not double-book
                svc.record_idem(d["idem"], int(q))
        self.resume_in_flight()
        if self.lm_manager is not None:
            # multi-owner filter (ISSUE 15): becoming cluster master
            # adopts master DUTIES (CNN book, train jobs, fair share) —
            # NOT every pool scope. A scope whose claimed owner is a
            # SURVIVOR stays that owner's, untouched; scopes of the dead
            # master (or unclaimed ones) load here only if their
            # rendezvous placement over the survivors lands on this host
            # (the scope's own successor adopted the rest via
            # _adopt_scopes_of, which ran first).
            owners = getattr(self.membership, "owners", None)
            alive = set(self.membership.members.alive_hosts())

            def keep(scope: str) -> bool:
                if owners is None:
                    return True
                claimed = owners.owner(scope)
                if claimed == self.host:
                    return True
                if claimed is not None and claimed in alive:
                    return False    # surviving owner keeps serving
                return place_scope(scope, self.config.hosts,
                                   alive) == self.host

            held_before = set(self.lm_manager.scope_names())
            loaded = False
            if snap is not None and "lm" in snap:
                self.lm_manager.load_wire(snap["lm"], keep_scope=keep)
                loaded = True
            if scale_wal:
                # scaling decisions WAL'd after the newest snapshot:
                # replay them exactly (group wire entries are
                # authoritative where their decision log is longer)
                self.lm_manager.apply_scale_wal(scale_wal,
                                                keep_scope=keep)
                loaded = True
            if pool_wal:
                # per-pool journal segments WAL'd after the newest
                # snapshot: replay per scope — a pool whose wal_seq moved
                # past the snapshot gets exactly its own newer journal
                replayed = self.lm_manager.apply_pool_wal(pool_wal,
                                                          keep_scope=keep)
                if replayed:
                    svc.metrics.record_counter("pool_wal_replayed",
                                               replayed)
                loaded = True
            if loaded:
                # per-scope fences: mint a strictly-higher epoch for every
                # NEWLY adopted pool/group scope, so the deposed master's
                # pool-directed stamps are rejected per pool — scopes this
                # host already held (a surviving owner becoming master)
                # keep their fence AND their claim untouched
                for scope in self.lm_manager.scope_names():
                    if scope in held_before:
                        continue
                    self.membership.scopes.fence(scope).mint(self.host)
                    svc.metrics.record_counter("pool_scope_adopted")
                    if owners is not None \
                            and owners.owner(scope) != self.host:
                        owners.claim(scope, self.host)
                        svc.metrics.record_counter("scope_owner_moves")
                self.lm_manager.on_adopt()
        if asp is not None:
            svc.spans.finish(
                asp, resumed=len(svc.scheduler.book.in_flight()))

    def resume_in_flight(self) -> None:
        """Reassign in-flight tasks stranded on dead hosts (including the
        dead coordinator) and re-dispatch everything still marked working —
        duplicates are rejected by the task book."""
        svc = self.service
        alive = set(self.membership.members.alive_hosts())
        for task in svc.scheduler.book.in_flight():
            if task.worker not in alive:
                candidates = sorted(alive - {task.worker})
                if not candidates:
                    continue
                svc.scheduler.book.reassign(
                    task, svc.scheduler.rng.choice(candidates),
                    svc.clock())
            svc._dispatch(task)
