"""Query-rate and processing-time metrics (SURVEY.md C8).

Reference semantics kept, bugs not (`mp4_machinelearning.py:623-677,
1016-1036`):
- Per finished task, record a *normalized* per-query processing time:
  ``elapsed / n_items * batch_size`` — the time a full standard query (400
  images) would have taken at this task's rate (`:656-662`).
- 30 s sliding window (SLIDING_WINDOW_SECONDS=10 × FACTOR=3, `:56-57`)
  pruned on read, not by a busy-spin thread (`:1016-1036` burns a core).
- Stats vector [avg, p25, p50, p75, stddev] (`:618-621`).
- c1/c2 surface real numbers — the reference *fabricates* AlexNet stats as
  0.95 × ResNet's and quartiles from the max average (`:1232-1267`).
"""
from __future__ import annotations

import statistics
import threading
import time
from collections.abc import Callable
from dataclasses import dataclass


@dataclass
class ProcessingStats:
    avg: float
    q1: float
    q2: float
    q3: float
    stddev: float
    n: int

    def as_list(self) -> list[float]:
        return [self.avg, self.q1, self.q2, self.q3, self.stddev]


def _percentile(sorted_vals: list[float], p: float) -> float:
    """numpy.percentile's default linear interpolation (`:620`), without
    pulling numpy into the control plane."""
    if not sorted_vals:
        return 0.0
    k = (len(sorted_vals) - 1) * p / 100.0
    f = int(k)
    c = min(f + 1, len(sorted_vals) - 1)
    return sorted_vals[f] + (sorted_vals[c] - sorted_vals[f]) * (k - f)


class MetricsTracker:
    def __init__(self, clock: Callable[[], float] = time.time,
                 window_s: float = 30.0) -> None:
        self.clock = clock
        self.window_s = window_s
        self._lock = threading.RLock()
        self._finished_images: dict[str, int] = {}
        self._finished_queries: dict[str, int] = {}
        # (finish_time, normalized_per_query_time) per model (`:662-665`)
        self._proc: dict[str, list[tuple[float, float]]] = {}
        # (finish_time, n_images) per model for the rate window (`:649-652`)
        self._images: dict[str, list[tuple[float, int]]] = {}
        # last-seen LM serving gauges per pool (prefix_hit_rate,
        # cached_tokens_saved, kv_blocks_free/used — serve/prefix_cache.py);
        # point-in-time values, not windowed series
        self._lm_gauges: dict[str, dict] = {}
        # last-seen QoS gateway gauges per pool (per-class queue depth,
        # reject rate, queue-wait p50/p99 — serve/gateway.py); the gateway
        # keeps its own windows, these are the flattened readback
        self._gw_gauges: dict[str, dict] = {}
        # last-seen autoscaler gauges per replica group (replica count,
        # draining count, decisions_total — serve/autoscaler.py)
        self._as_gauges: dict[str, dict] = {}
        # named event counters (wal_skipped_standby_down, stale-epoch
        # rejections, …) — node-LOCAL observability, deliberately not
        # replicated in to_wire/load_wire: a counter describes what THIS
        # node saw, adopting another node's count would double-report
        self._counters: dict[str, int] = {}

    # -- recording --------------------------------------------------------

    def record_counter(self, name: str, n: int = 1) -> int:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(n)
            return self._counters[name]

    def counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def record_task(self, model: str, n_items: int, elapsed_s: float,
                    batch_size: int) -> None:
        now = self.clock()
        norm = (elapsed_s / max(n_items, 1)) * batch_size
        with self._lock:
            self._finished_images[model] = (
                self._finished_images.get(model, 0) + n_items)
            self._proc.setdefault(model, []).append((now, norm))
            self._images.setdefault(model, []).append((now, n_items))

    def record_query_done(self, model: str) -> None:
        with self._lock:
            self._finished_queries[model] = (
                self._finished_queries.get(model, 0) + 1)

    def record_lm_gauges(self, pool: str, gauges: dict) -> None:
        """Latest LM prefix-cache gauges for ``pool`` (overwritten per
        read — gauges, not counters; the C8 surface reads them back via
        `lm_gauges`)."""
        with self._lock:
            self._lm_gauges[pool] = dict(gauges)

    def record_gateway_gauges(self, pool: str, gauges: dict) -> None:
        """Latest QoS gateway gauges for ``pool`` (same overwrite-per-read
        contract as `record_lm_gauges`; read back via `gateway_gauges`)."""
        with self._lock:
            self._gw_gauges[pool] = dict(gauges)

    def record_autoscale_gauges(self, group: str, gauges: dict) -> None:
        """Latest autoscaler gauges for replica ``group`` (same
        overwrite-per-read contract; read back via `autoscale_gauges`)."""
        with self._lock:
            self._as_gauges[group] = dict(gauges)

    # -- reading ----------------------------------------------------------

    def _prune(self, series: list[tuple[float, float]] | list[tuple[float, int]],
               now: float) -> None:
        cutoff = now - self.window_s
        while series and series[0][0] < cutoff:
            series.pop(0)

    def finished_images(self, model: str) -> int:
        with self._lock:
            return self._finished_images.get(model, 0)

    def finished_queries(self, model: str) -> int:
        with self._lock:
            return self._finished_queries.get(model, 0)

    def image_rate(self, model: str) -> float:
        """Images/sec over the sliding window."""
        now = self.clock()
        with self._lock:
            series = self._images.setdefault(model, [])
            self._prune(series, now)
            return sum(n for _, n in series) / self.window_s

    def query_rate(self, model: str, batch_size: int) -> float:
        """Standard-size queries/sec over the sliding window (`:1027-1028`)."""
        return self.image_rate(model) / max(batch_size, 1)

    def processing_stats(self, model: str) -> ProcessingStats | None:
        """[avg, p25, p50, p75, stddev] of normalized per-query times in the
        window — honest numbers for c2 (`:618-621`), None when no data."""
        now = self.clock()
        with self._lock:
            series = self._proc.setdefault(model, [])
            self._prune(series, now)
            vals = sorted(t for _, t in series)
        if not vals:
            return None
        return ProcessingStats(
            avg=statistics.fmean(vals),
            q1=_percentile(vals, 25), q2=_percentile(vals, 50),
            q3=_percentile(vals, 75),
            stddev=statistics.pstdev(vals) if len(vals) > 1 else 0.0,
            n=len(vals))

    def reset_processing(self, model: str | None = None) -> None:
        """Drop the windowed timing series for one model (or all): the
        fair scheduler's `avg_query_time` signal must not carry one-time
        compile cost, so a warm-up pass resets here and the first REAL
        query starts the steady-state signal (the reference's 7/3 worked
        example is a steady-state split). Finished-counters and LM gauges
        survive — they are totals, not service-time signal."""
        with self._lock:
            if model is None:
                self._proc.clear()
                self._images.clear()
            else:
                self._proc.pop(model, None)
                self._images.pop(model, None)

    def lm_gauges(self, pool: str) -> dict | None:
        with self._lock:
            g = self._lm_gauges.get(pool)
            return dict(g) if g is not None else None

    def gateway_gauges(self, pool: str) -> dict | None:
        with self._lock:
            g = self._gw_gauges.get(pool)
            return dict(g) if g is not None else None

    def autoscale_gauges(self, group: str) -> dict | None:
        with self._lock:
            g = self._as_gauges.get(group)
            return dict(g) if g is not None else None

    def avg_query_time(self, model: str) -> float:
        """Feed for the fair scheduler (`model_average_inference_time`,
        `:504-506`). 0.0 = no history yet."""
        s = self.processing_stats(model)
        return s.avg if s else 0.0

    # -- Prometheus text exposition (ISSUE 6 tentpole) -------------------

    def prometheus_text(self, node: str,
                        extra_counters: dict[str, int] | None = None,
                        extra_gauges: dict[str, float] | None = None) -> str:
        """Text-format exposition (prometheus.io/docs/instrumenting/
        exposition_formats) of everything this tracker holds: event
        counters, per-model rates/percentiles, LM prefix-cache and QoS
        gateway gauges. ``extra_counters``/``extra_gauges`` merge
        process-wide series the tracker doesn't own (comm/retry.py
        counters, span-store depth) into the same scrape."""
        esc = (lambda s: str(s).replace("\\", "\\\\").replace('"', '\\"'))
        lines: list[str] = []

        def emit(metric: str, kind: str, value, **labels) -> None:
            if not any(ln.startswith(f"# TYPE {metric} ")
                       for ln in lines):
                lines.append(f"# TYPE {metric} {kind}")
            lab = ",".join(f'{k}="{esc(v)}"' for k, v
                           in [("node", node), *sorted(labels.items())])
            lines.append(f"{metric}{{{lab}}} {float(value):g}")

        with self._lock:
            counters = dict(self._counters)
            models = sorted(set(self._finished_images)
                            | set(self._finished_queries))
            lm_gauges = {p: dict(g) for p, g in self._lm_gauges.items()}
            gw_gauges = {p: dict(g) for p, g in self._gw_gauges.items()}
            as_gauges = {p: dict(g) for p, g in self._as_gauges.items()}
        for name, v in sorted({**counters,
                               **(extra_counters or {})}.items()):
            emit("idunno_events_total", "counter", v, name=name)
        for m in models:
            emit("idunno_finished_images_total", "counter",
                 self.finished_images(m), model=m)
            emit("idunno_finished_queries_total", "counter",
                 self.finished_queries(m), model=m)
            emit("idunno_image_rate", "gauge", self.image_rate(m), model=m)
            ps = self.processing_stats(m)
            if ps is not None:
                for q, v in (("avg", ps.avg), ("p25", ps.q1),
                             ("p50", ps.q2), ("p75", ps.q3)):
                    emit("idunno_processing_seconds", "gauge", v,
                         model=m, quantile=q)
        for pool, g in sorted(lm_gauges.items()):
            for k, v in sorted(g.items()):
                if isinstance(v, (int, float)):
                    emit("idunno_lm_gauge", "gauge", v, pool=pool, name=k)
        for pool, g in sorted(gw_gauges.items()):
            for k, v in sorted(g.items()):
                if isinstance(v, (int, float)):
                    emit("idunno_gateway_gauge", "gauge", v,
                         pool=pool, name=k)
        for group, g in sorted(as_gauges.items()):
            for k, v in sorted(g.items()):
                if isinstance(v, (int, float)):
                    emit("idunno_autoscale_gauge", "gauge", v,
                         group=group, name=k)
        for name, v in sorted((extra_gauges or {}).items()):
            emit("idunno_gauge", "gauge", v, name=name)
        return "\n".join(lines) + "\n"

    # -- failover serialization ------------------------------------------

    def to_wire(self) -> dict:
        with self._lock:
            return {"finished_images": dict(self._finished_images),
                    "finished_queries": dict(self._finished_queries),
                    "proc": {m: [list(x) for x in v]
                             for m, v in self._proc.items()},
                    "images": {m: [list(x) for x in v]
                               for m, v in self._images.items()},
                    "lm_gauges": {m: dict(g) for m, g
                                  in self._lm_gauges.items()},
                    "gw_gauges": {m: dict(g) for m, g
                                  in self._gw_gauges.items()},
                    "as_gauges": {m: dict(g) for m, g
                                  in self._as_gauges.items()}}

    def load_wire(self, d: dict) -> None:
        with self._lock:
            self._finished_images = {k: int(v) for k, v
                                     in d.get("finished_images", {}).items()}
            self._finished_queries = {k: int(v) for k, v
                                      in d.get("finished_queries", {}).items()}
            self._proc = {m: [(float(a), float(b)) for a, b in v]
                          for m, v in d.get("proc", {}).items()}
            self._images = {m: [(float(a), int(b)) for a, b in v]
                            for m, v in d.get("images", {}).items()}
            self._lm_gauges = {m: dict(g) for m, g
                               in d.get("lm_gauges", {}).items()}
            self._gw_gauges = {m: dict(g) for m, g
                               in d.get("gw_gauges", {}).items()}
            self._as_gauges = {m: dict(g) for m, g
                               in d.get("as_gauges", {}).items()}
