"""Admission policy for the LM serving front door (ISSUE 4).

The gateway (`serve/gateway.py`) decides *whether* a request may enter a
pool and *when* it is dispatched; this module holds the policy pieces the
rest of the stack needs to name without importing the queue machinery:

- the priority classes (`interactive` strictly before `batch`),
- the typed rejection (`AdmissionShed`, with a machine-parseable reason
  that survives a trip through an RPC error string — the manager journal
  parses it back out with `shed_reason` to record the request terminal),
- the backpressure rule (`BackpressureConfig.pressure_reason`) computed
  from live pool gauges: requests queued upstream of a slot, slot
  occupancy, and free KV blocks on paged pools.

Design follows Clockwork (Gujarati et al., OSDI 2020): reject early and
explicitly at the front door, where per-class latency targets are still
salvageable, rather than letting an unbounded inbox melt queue-wait
percentiles for everyone (see PAPERS.md).
"""
from __future__ import annotations

import re
from dataclasses import dataclass

# Class order IS dispatch order: every queued interactive request is
# dispatched before any batch request, regardless of deadlines.
PRIORITIES = ("interactive", "batch")

SHED_REASONS = ("quota", "queue_full", "backpressure", "expired")

_SHED_RE = re.compile(r"shed\[([a-z_]+)\]")


class AdmissionShed(ValueError):
    """Typed front-door rejection. Subclasses ValueError so existing RPC
    error plumbing (`serve/control.py` wraps handler ValueErrors into
    `{"error": str(e)}`) carries it unchanged; the reason is re-parsed on
    the far side with `shed_reason`."""

    def __init__(self, reason: str, detail: str = "") -> None:
        assert reason in SHED_REASONS, reason
        self.reason = reason
        self.detail = detail
        super().__init__(f"shed[{reason}]" + (f": {detail}" if detail else ""))


def shed_reason(text: str) -> str | None:
    """Reason parsed from a stringified AdmissionShed (None = not a shed).
    The manager's `_forward` uses this to classify a remote ValueError as
    a journal-terminal shed vs an infrastructure failure."""
    m = _SHED_RE.search(text or "")
    return m.group(1) if m else None


def is_prefill_heavy(prompt_len: int, threshold: int) -> bool:
    """DistServe's split criterion at request-routing granularity (Zhong
    et al., OSDI 2024): an admission whose prompt is at least
    ``threshold`` tokens is PREFILL-heavy — its cost is dominated by the
    compute-bound prompt pass, and interleaving it with latency-bound
    decode traffic inflates decode queue waits. Replica groups
    (`serve/lm_manager.py:_route_group_locked`) route these to the
    group's `prefill_chunk`-tuned replica. ``threshold`` <= 0 disables
    the split."""
    return threshold > 0 and int(prompt_len) >= int(threshold)


@dataclass(frozen=True)
class BackpressureConfig:
    """Occupancy-driven shed thresholds.

    ``backlog`` below = requests in the system but not yet retired
    (gateway queues + pool inbox + server queue + live slots). With all
    slots busy, a backlog of ``slots * (1 + k)`` means a new arrival
    waits ~k full service quanta for a slot — so ``k`` is a queue-wait
    bound expressed in units of per-request service time. Batch sheds at
    a small k, interactive at a larger one, and the gap is what keeps
    interactive p99 queue wait bounded under overload while batch takes
    the sheds.

    ``min_free_kv_frac`` sheds batch early on paged pools when the block
    pool runs dry: free blocks are the prefix cache's working set, and
    admitting more batch bulk when residency is exhausted trades cached
    prefills for queue depth (vLLM's watermark heuristic).
    """

    batch_wait_slack: float = 2.0
    interactive_wait_slack: float = 4.0
    min_free_kv_frac: float = 0.125

    def pressure_reason(self, priority: str, gauges: dict) -> str | None:
        """Shed detail string when ``gauges`` say the pool is too loaded
        for a new ``priority`` request, else None. ``gauges`` keys:
        ``waiting`` (queued upstream of a slot, gateway depth included),
        ``live``, ``slots``, and optionally ``kv_blocks_free`` /
        ``kv_blocks_total`` (0/absent on unpaged pools)."""
        slots = max(int(gauges.get("slots", 1)), 1)
        backlog = int(gauges.get("waiting", 0)) + int(gauges.get("live", 0))
        slack = (self.interactive_wait_slack if priority == "interactive"
                 else self.batch_wait_slack)
        if backlog >= slots * (1.0 + slack):
            return (f"backlog {backlog} >= {slots} slots * "
                    f"(1 + {slack:g} slack)")
        if priority == "batch":
            total = int(gauges.get("kv_blocks_total", 0))
            if total > 0:
                free = int(gauges.get("kv_blocks_free", 0))
                if free / total < self.min_free_kv_frac:
                    return (f"free KV blocks {free}/{total} < "
                            f"{self.min_free_kv_frac:g} floor")
        return None
