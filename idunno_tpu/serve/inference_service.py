"""The distributed inference serving path (SURVEY.md C6, C7, C9, C11).

Call path, re-architected from the reference's §3.2 stack:

  client ``submit_query`` ──INFERENCE──► acting master
      master: FairScheduler.assign → per-task ──JOB──► workers
      worker: queue → engine (jit batched forward on its chips)
              ──RESULT──► acting master (NOT a 10-way TCP broadcast,
                          `mp4_machinelearning.py:603-613`)
      master: TaskBook.mark_finished, metrics, result accumulation

Failure handling on the master: membership LEAVE → in-flight tasks of the
dead worker reassigned to ring successors and re-dispatched
(`transfer_failed_inference_work`, `:706-760`); straggler monitor re-sends
tasks stuck past the timeout with the comparison fixed (`:809-830`, bug
`:822`) and actually enabled (the reference ships it switched off, `:1277`).

Workers execute jobs from a queue: the transport handler only enqueues, so
dispatch never blocks on inference. The runtime drives ``process_jobs_once``
from a thread; tests call it directly for determinism.
"""
from __future__ import annotations

import threading
import time
import uuid
from collections.abc import Callable
from dataclasses import dataclass
from typing import Any, Protocol

from idunno_tpu.comm.message import Message
from idunno_tpu.comm.retry import call_with_retry
from idunno_tpu.comm.transport import Transport, TransportError
from idunno_tpu.config import ClusterConfig
from idunno_tpu.membership.epoch import (check_payload, observe_payload,
                                         reply_is_stale)
from idunno_tpu.membership.service import MembershipService
from idunno_tpu.scheduler.fair import FairScheduler
from idunno_tpu.scheduler.tasks import Task, WORKING
from idunno_tpu.serve.metrics import MetricsTracker
from idunno_tpu.utils.spans import stamp_trace, trace_from_payload
from idunno_tpu.utils.types import MemberStatus, MessageType

SERVICE = "inference"
RESULT_SERVICE = "result"


class Engine(Protocol):
    """What a worker needs from its model engine (the real
    ``idunno_tpu.engine.InferenceEngine`` or a test fake)."""

    def infer(self, name: str, start: int, end: int,
              dataset_root: str | None = None) -> Any: ...


@dataclass
class Job:
    model: str
    qnum: int
    start: int
    end: int
    dataset: str | None
    # dispatch stamp echoed in error reports so a stale report about an
    # OLD assignment can't be mistaken for the current one
    assigned: float = 0.0
    # (trace_id, parent_span_id) riding the JOB payload — the worker span
    # parents under the master's dispatch span
    trace: tuple | None = None


class InferenceServiceError(Exception):
    pass


class InferenceService:
    def __init__(self, host: str, config: ClusterConfig,
                 transport: Transport, membership: MembershipService,
                 engine: Engine, metrics: MetricsTracker | None = None,
                 scheduler: FairScheduler | None = None,
                 dataset_root: str | None = None,
                 clock: Callable[[], float] = time.time) -> None:
        self.host = host
        self.config = config
        self.transport = transport
        self.membership = membership
        self.engine = engine
        self.clock = clock
        self.metrics = metrics or MetricsTracker(clock=clock)
        self.scheduler = scheduler or FairScheduler(config, clock=clock)
        self.dataset_root = dataset_root
        # synchronous standby write-ahead invoked at the end of every
        # master-side submit as wal_hook(model, qnum, tasks, dataset, idem)
        # (serve/node.py wires it to FailoverManager.wal_append);
        # None = periodic-only replication
        self.wal_hook = None

        # coordinator state
        self._qnum: dict[str, int] = {}          # per-model counter (`:965-966`)
        self._results: dict[tuple[str, int], list[tuple[str, str, float]]] = {}
        # per-model weight-provenance markers seen in RESULTs ("pretrained"
        # / "store" / "random") — random init must never pass as real
        # classifications
        self._weights_seen: dict[str, set[str]] = {}
        # per-model engine-failure reports: proof a model EXECUTED (and
        # failed) somewhere — it is not cold-compiling, so the straggler
        # monitor's first-compile grace must not shield it
        self._task_errors: dict[str, int] = {}
        # client idempotency keys → booked qnum: a retry after a lost ACK
        # returns the original booking instead of double-submitting
        # (replicated in the failover snapshot + WAL deltas)
        self._idem: dict[str, int] = {}
        # SpanStore wired by serve/node.py; None = tracing off everywhere
        self.spans = None
        # (model, qnum) → (trace_id, schedule_span_id): dispatch /
        # re-dispatch / collect spans of a query all hang off its schedule
        # span. Master-local (like metrics counters) — bounded FIFO.
        self._trace_ctx: dict[tuple[str, int], tuple] = {}
        self._results_lock = threading.RLock()

        # worker state
        self._jobs: list[Job] = []
        self._pending_results: list[Message] = []   # computed, undelivered
        self._jobs_lock = threading.RLock()
        self._jobs_available = threading.Event()
        # background member-change re-dispatch sends (join_reassign_dispatch)
        self._reassign_threads: list[threading.Thread] = []

        transport.serve(SERVICE, self._handle_inference)
        transport.serve(RESULT_SERVICE, self._handle_result)
        membership.on_change(self._on_member_change)

    # ------------------------------------------------------------------ #
    # client API
    # ------------------------------------------------------------------ #

    def _master_call(self, msg: Message) -> Message:
        """Primary→standby failover (`send_inference_command`, `:956-963`)
        — plus bounded backoff retries per target (safe: the message
        carries an idempotency key, so a retry after a lost ACK dedupes
        server-side) and fence-aware rerouting: a "not acting master" /
        stale-epoch rejection moves on to the next target instead of
        failing the submit."""
        targets = [self.membership.acting_master()]
        for t in (self.config.coordinator, self.config.standby_coordinator):
            if t not in targets:
                targets.append(t)
        last: object = None
        for t in targets:
            if t == self.host:
                out = self._handle_inference(SERVICE, msg)
            else:
                try:
                    out = call_with_retry(
                        lambda t=t: self.transport.call(t, SERVICE, msg,
                                                        timeout=30.0),
                        attempts=self.config.rpc_retry_attempts,
                        base_s=self.config.rpc_retry_base_s,
                        cap_s=self.config.rpc_retry_cap_s,
                        deadline_s=self.config.rpc_retry_deadline_s)
                except TransportError as e:
                    last = e
                    continue
            if out is None:
                continue
            observe_payload(self.membership.epoch, out.payload)
            if out.type is MessageType.ERROR:
                if out.payload.get("not_master") \
                        or out.payload.get("stale_epoch"):
                    last = out.payload.get("error")
                    continue        # deposed/unfenced peer: try the next
                raise InferenceServiceError(
                    out.payload.get("error", "inference error"))
            return out
        raise InferenceServiceError(f"no reachable coordinator: {last}")

    def submit_query(self, model: str, start: int, end: int,
                     dataset: str | None = None) -> int:
        """Submit one query range; returns the assigned query number.
        ``dataset`` overrides this node's default root for the query —
        e.g. ``store://<name>`` resolves against a dataset published into
        the replicated store on every worker (`engine.data_store`)."""
        # one idempotency key per LOGICAL submit, constant across every
        # retry/failover attempt inside _master_call: a lost ACK retried
        # against the same (or the newly adopted) master returns the
        # original qnum instead of booking twice
        payload = {"model": model, "start": start, "end": end,
                   "dataset": dataset or self.dataset_root,
                   "idem": f"{self.host}:{uuid.uuid4().hex}"}
        sp = None
        if self.spans is not None:
            sp = self.spans.start("cnn.submit",
                                  attrs={"model": model, "start": start,
                                         "end": end})
            stamp_trace(payload, sp.ctx)
        try:
            out = self._master_call(Message(
                MessageType.INFERENCE, self.host, payload))
        except Exception:
            if sp is not None:
                self.spans.finish(sp, error=True)
            raise
        if sp is not None:
            self.spans.finish(sp, qnum=int(out.payload["qnum"]))
        return int(out.payload["qnum"])

    def inference(self, model: str, start: int, end: int,
                  pace_s: float | None = None,
                  sleep: Callable[[float], None] = time.sleep,
                  dataset: str | None = None) -> list[int]:
        """The `inference <start> <end> <model>` verb: chunk the range into
        standard-batch queries, one submission per pacing interval
        (`Server.inference`, `:1104-1109`)."""
        bs = self.config.query_batch_size
        pace = self.config.query_interval_s if pace_s is None else pace_s
        qnums = []
        cursor = start
        while cursor <= end:
            chunk_end = min(cursor + bs - 1, end)
            qnums.append(self.submit_query(model, cursor, chunk_end,
                                           dataset=dataset))
            cursor = chunk_end + 1
            if cursor <= end and pace > 0:
                sleep(pace)
        return qnums

    def results(self, model: str, qnum: int) -> list[tuple[str, str, float]]:
        with self._results_lock:
            return list(self._results.get((model, qnum), []))

    def all_results(self) -> dict[str, list[tuple[str, str, float]]]:
        """c4 view: "model qnum" → records (`:1208-1211`)."""
        with self._results_lock:
            return {f"{m} {q}": list(v)
                    for (m, q), v in sorted(self._results.items())}

    def query_done(self, model: str, qnum: int) -> bool:
        return self.scheduler.book.query_done(model, qnum)

    def query_failed(self, model: str, qnum: int) -> bool:
        """True when part of the query permanently failed (retry cap):
        waiting for `query_done` would block forever."""
        return self.scheduler.book.query_failed(model, qnum)

    def models_seen(self) -> list[str]:
        """Models with at least one known query — the single source for the
        shell's c1/c2 and the remote stats verb (query counters plus the
        task book, which can know models the counters don't after a
        failover adoption)."""
        models = {m for m, _ in self.scheduler.book.queries()}
        with self._results_lock:
            models.update(self._qnum)
        return sorted(models)

    def weights_provenance(self) -> dict[str, str]:
        """Per-model weight provenance aggregated over RESULTs:
        "pretrained" | "store" | "random" | "unknown", or "mixed(...)" if workers
        disagree (e.g. one node has the checkpoint cached, another not)."""
        with self._results_lock:
            out = {}
            for m, seen in self._weights_seen.items():
                out[m] = (next(iter(seen)) if len(seen) == 1
                          else "mixed(" + ",".join(sorted(seen)) + ")")
            return out

    # ------------------------------------------------------------------ #
    # coordinator side
    # ------------------------------------------------------------------ #

    def _handle_inference(self, service: str, msg: Message) -> Message | None:
        # fence first, before either branch can touch scheduler state: a
        # verb stamped below our epoch high-water comes from a deposed
        # coordinator — reject (typed), never act; the reply deposes the
        # sender. Unstamped client submissions pass untouched.
        stale = check_payload(self.membership.epoch, msg.payload, self.host)
        if stale is not None:
            return stale
        if msg.type is MessageType.INFERENCE:      # client submission
            if not self.membership.is_acting_master:
                return Message(MessageType.ERROR, self.host,
                               {"error": f"{self.host} not acting master",
                                "not_master": True})
            p = msg.payload
            return self._master_submit(p["model"], int(p["start"]),
                                       int(p["end"]), p.get("dataset"),
                                       idem=p.get("idem"),
                                       trace=trace_from_payload(p))
        if msg.type is MessageType.JOB:            # dispatched task
            p = msg.payload
            with self._jobs_lock:
                self._jobs.append(Job(model=p["model"], qnum=int(p["qnum"]),
                                      assigned=float(p.get("assigned", 0.0)),
                                      start=int(p["start"]),
                                      end=int(p["end"]),
                                      dataset=p.get("dataset"),
                                      trace=trace_from_payload(p)))
                self._jobs_available.set()
            return Message(MessageType.ACK, self.host)
        return Message(MessageType.ERROR, self.host,
                       {"error": f"bad inference verb {msg.type}"})

    def _master_submit(self, model: str, start: int, end: int,
                       dataset: str | None,
                       idem: str | None = None,
                       trace: tuple | None = None) -> Message:
        workers = self._eligible_workers()     # before reserving the idem
        # key: a failed submit must stay retryable as a fresh booking
        if not workers:
            return Message(MessageType.ERROR, self.host,
                           {"error": "no alive workers"})
        with self._results_lock:                 # _qnum guarded like results
            # idempotency: check-and-reserve under the same lock as the
            # qnum bump, so two concurrent retries of one logical submit
            # can't both book (the first wins, the second reads its qnum)
            if idem is not None and idem in self._idem:
                dup = self._idem[idem]
                if self.spans is not None and trace is not None:
                    # retry after a lost ACK: the dedup is a span too, so
                    # the trace shows both attempts and ONE booking
                    self.spans.record(
                        "cnn.schedule", trace=trace[0], parent=trace[1],
                        t_start=self.spans.clock(),
                        attrs={"model": model, "qnum": dup,
                               "duplicate": True})
                return Message(MessageType.ACK, self.host,
                               {"qnum": dup, "duplicate": True})
            self.scheduler.avg_query_time = {
                m: self.metrics.avg_query_time(m)
                for m in set(self._qnum) | {model}}
            qnum = self._qnum.get(model, 0) + 1
            self._qnum[model] = qnum
            if idem is not None:
                self._idem[idem] = qnum
                if len(self._idem) > 4096:     # bounded: oldest keys fall
                    for k in list(self._idem)[:1024]:
                        del self._idem[k]
        ssp = None
        if self.spans is not None:
            # mints a fresh trace when the client didn't stamp one (e.g. a
            # shell-local submit): every query is traceable either way
            ssp = self.spans.start(
                "cnn.schedule",
                trace=trace[0] if trace else None,
                parent=trace[1] if trace else None,
                attrs={"model": model, "qnum": qnum,
                       "start": start, "end": end})
            with self._results_lock:
                self._trace_ctx[(model, qnum)] = (ssp.trace_id, ssp.span_id)
                if len(self._trace_ctx) > 4096:
                    for k in list(self._trace_ctx)[:1024]:
                        del self._trace_ctx[k]
        tasks = self.scheduler.assign(model, qnum, start, end, workers,
                                      dataset=dataset)
        for t in tasks:
            self._dispatch(t)
        if ssp is not None:
            self.spans.finish(ssp, tasks=len(tasks))
        # write-ahead to the standby BEFORE the client sees the ack: an
        # acked query must survive an immediate coordinator death, not
        # only one that lands after the next periodic replication tick
        # (FailoverManager.wal_append — a tiny per-query delta, never the
        # full snapshot, so the ack path stays O(1); best-effort when the
        # standby is down, like the periodic loop; wired by serve/node.py)
        if self.wal_hook is not None:
            self.wal_hook(model, qnum, tasks, dataset, idem)
        return Message(MessageType.ACK, self.host, {"qnum": qnum})

    # -- idempotency-map replication glue (FailoverManager) ---------------

    def record_idem(self, idem: str, qnum: int) -> None:
        with self._results_lock:
            self._idem[idem] = int(qnum)

    def idem_to_wire(self) -> dict[str, int]:
        with self._results_lock:
            return dict(self._idem)

    def idem_load_wire(self, wire: dict[str, int]) -> None:
        with self._results_lock:
            for k, v in wire.items():
                self._idem.setdefault(k, int(v))

    def trace_of(self, model: str, qnum: int) -> str | None:
        """Trace id of a scheduled query (the `trace` verb resolves
        ``model qnum`` through this); None when untraced or evicted."""
        with self._results_lock:
            tr = self._trace_ctx.get((model, int(qnum)))
            return tr[0] if tr else None

    def _eligible_workers(self) -> list[str]:
        """All alive hosts serve as workers, the coordinator included
        (`send_inference_work` local-execute branch, `:764-791`)."""
        return self.membership.members.alive_hosts()

    def _dispatch(self, task: Task) -> None:
        # On send failure, reassign on the spot rather than waiting for the
        # failure detector — with a cumulative exclusion set so several
        # simultaneously-dead workers can't ping-pong the dispatch forever.
        tried: set[str] = set()
        tr = None
        if self.spans is not None:
            with self._results_lock:
                tr = self._trace_ctx.get((task.model, task.qnum))
        while True:
            # snapshot the assignment this attempt is for (atomic — a torn
            # read could pair the new worker with the old stamp), and
            # rebuild the message per attempt: the echoed ``assigned``
            # stamp must match the CURRENT booking or the worker's error
            # report about it would be dropped as stale
            worker, stamp, state = self.scheduler.book.assignment(task)
            if state != WORKING:
                return          # finished/failed while queued for dispatch
            msg = Message(MessageType.JOB, self.host,
                          {"model": task.model, "qnum": task.qnum,
                           "start": task.start, "end": task.end,
                           "dataset": task.dataset,
                           "assigned": stamp,
                           "epoch": list(self.membership.epoch.view())})
            dsp = None
            if tr is not None:
                # one span per ATTEMPT: re-dispatch after a dead worker
                # shows up as a second span naming the new worker
                dsp = self.spans.start(
                    "cnn.dispatch", trace=tr[0], parent=tr[1],
                    attrs={"model": task.model, "qnum": task.qnum,
                           "start": task.start, "end": task.end,
                           "worker": worker})
                stamp_trace(msg.payload, (tr[0], dsp.span_id))
            if worker == self.host:
                self._handle_inference(SERVICE, msg)
                if dsp is not None:
                    self.spans.finish(dsp, local=True)
                return
            try:
                out = self.transport.call(worker, SERVICE, msg,
                                          timeout=30.0)
                if dsp is not None:
                    self.spans.finish(dsp)
                if reply_is_stale(self.membership.epoch, out):
                    # the worker has seen a higher epoch: we are deposed.
                    # Step down — do NOT treat this as a dead worker and
                    # re-dispatch (that is exactly the split-brain double
                    # execution fencing exists to prevent); the real
                    # master owns this task now.
                    return
                return
            except TransportError:
                if dsp is not None:
                    self.spans.finish(dsp, error="TransportError")
                tried.add(worker)
                alive = [h for h in self._eligible_workers()
                         if h not in tried]
                if not alive:
                    return    # straggler monitor will retry later
                moved = self.scheduler.book.reassign_if_current(
                    task, worker, stamp,
                    self.scheduler.rng.choice(alive), self.clock())
                if moved is None:
                    # another thread re-booked (second death, straggler
                    # pass, error report) while this send was in flight;
                    # that thread owns the dispatch now — dropping here
                    # prevents double-moves and double-execution
                    return
                task = moved

    def _handle_result(self, service: str, msg: Message) -> Message | None:
        """Acting master accumulates results + metrics (`:623-704`);
        error reports from workers re-dispatch the task immediately."""
        p = msg.payload
        # observe (never reject) the worker's fence view: the work itself
        # is valid at any epoch (the book dedupes), but a result stamped
        # ABOVE our view means we were deposed while partitioned — the
        # observe demotes us and the is_acting_master checks below hand
        # the result back to the worker for the real master
        observe_payload(self.membership.epoch, p)
        model, qnum = p["model"], int(p["qnum"])
        start, end = int(p["start"]), int(p["end"])
        if p.get("error"):
            if not self.membership.is_acting_master:
                # keep the report queued worker-side for the real master
                return Message(MessageType.ERROR, self.host,
                               {"error": f"{self.host} not acting master",
                                "not_master": True})
            assigned = float(p.get("assigned", 0.0))
            task = next(
                (t for t in self.scheduler.book.in_flight(msg.sender)
                 if t.model == model and t.qnum == qnum
                 and t.start == start and t.end == end
                 # the echoed dispatch stamp ties the report to THIS
                 # assignment: a stale report (queued while partitioned)
                 # about an older assignment of the same range to the
                 # same worker must not burn the current attempt's budget
                 and abs(t.t_assigned - assigned) < 1e-6), None)
            if task is None:              # stale (already moved/finished)
                return Message(MessageType.ACK, self.host,
                               {"duplicate": True})
            # evidence of life for the model: it executed and FAILED, so
            # the cold-compile straggler grace no longer applies to it
            # (master-local; a failover resets it, costing at most one
            # grace period)
            self._task_errors[model] = self._task_errors.get(model, 0) + 1
            # the report is about THIS (sender, stamp) assignment — the
            # snapshot keeps a concurrent re-booking from being moved twice
            self._redispatch_or_fail(
                task, f"engine error on {msg.sender}: {p['error']}",
                snapshot=(msg.sender, assigned))
            return Message(MessageType.ACK, self.host)
        task = self.scheduler.book.mark_finished(model, qnum, start, end,
                                                 self.clock())
        if task is None:
            if self.membership.is_acting_master:
                # genuinely stale/duplicate — accept and drop
                return Message(MessageType.ACK, self.host,
                               {"duplicate": True})
            # unknown task on a NON-master (e.g. the standby before
            # adoption): refuse, so the worker keeps the result queued
            # instead of believing it was delivered.
            return Message(MessageType.ERROR, self.host,
                           {"error": f"{self.host} has no record of task",
                            "not_master": True})
        records = [tuple(r) for r in p["records"]]
        with self._results_lock:
            self._results.setdefault((model, qnum), []).extend(records)
            self._weights_seen.setdefault(model, set()).add(
                p.get("weights", "unknown"))
        self.metrics.record_task(model, task.n_items,
                                 float(p["elapsed_s"]),
                                 self.config.query_batch_size)
        done = self.scheduler.book.query_done(model, qnum)
        if done:
            self.metrics.record_query_done(model)
        tctx = trace_from_payload(p)
        if self.spans is not None and tctx is not None:
            now = self.spans.clock()
            self.spans.record("cnn.collect", trace=tctx[0], parent=tctx[1],
                              t_start=now, t_end=now,
                              attrs={"model": model, "qnum": qnum,
                                     "start": start, "end": end,
                                     "n": len(records),
                                     "worker": msg.sender,
                                     "query_done": done})
        return Message(MessageType.ACK, self.host)

    # -- failure / straggler handling (master) ----------------------------

    def _on_member_change(self, host: str, old: MemberStatus | None,
                          new: MemberStatus) -> None:
        if new is not MemberStatus.LEAVE or not self.membership.is_acting_master:
            return
        alive = self._eligible_workers()
        # book mutation is synchronous (tasks re-booked before returning);
        # only the network sends go off-thread: this callback runs on the
        # membership monitor loop, and a dispatch to a PARTITIONED
        # successor blocks on the full RPC timeout — failure detection for
        # other hosts must not stall behind it (same discipline as
        # lm_manager._on_member_change). The stale-snapshot guards in
        # _dispatch/_redispatch_or_fail keep the now-concurrent paths from
        # double-moving shared tasks.
        tasks = self.scheduler.reassign_failed(host, alive)
        if not tasks:
            return

        def _safe_dispatch(t: Task) -> None:
            try:
                self._dispatch(t)
            except Exception:  # noqa: BLE001 - a failed send must not
                # abandon the task silently; the straggler monitor retries
                import logging
                logging.getLogger("idunno.serving").warning(
                    "reassignment dispatch of %s#%s [%s, %s] failed",
                    t.model, t.qnum, t.start, t.end, exc_info=True)

        # one thread per task: a partitioned successor costs ITS task the
        # RPC timeout, not every later task's dispatch latency too. The
        # threads are tracked so tests (and shutdown paths) can join them
        # — the InProc transport's determinism contract is preserved via
        # `join_reassign_dispatch`, not by blocking the monitor loop here.
        for t in tasks:
            th = threading.Thread(target=_safe_dispatch, args=(t,),
                                  daemon=True,
                                  name=f"{self.host}-reassign")
            # start before recording: joining an unstarted thread raises
            th.start()
            with self._jobs_lock:
                self._reassign_threads = [
                    x for x in self._reassign_threads if x.is_alive()] + [th]

    def join_reassign_dispatch(self, timeout: float = 5.0) -> None:
        """Wait for in-flight member-change re-dispatch sends (they run on
        background threads so a partitioned successor can't stall the
        membership monitor loop). Deterministic tests call this between
        `monitor_once` and their job pump."""
        with self._jobs_lock:
            threads = list(self._reassign_threads)
        deadline = time.monotonic() + timeout
        for th in threads:
            th.join(timeout=max(0.0, deadline - time.monotonic()))

    # a model with NO completed task cluster-wide yet is probably
    # compiling on every worker at once (first TPU compile of a shape is
    # ~40-80 s, well past straggler_timeout_s): give its never-moved tasks
    # this grace so the monitor doesn't bounce the first query between
    # equally-cold workers and burn its retry cap on compiles. One grace
    # per task (reassign resets t_assigned, so per-move grace would
    # multiply time-to-FAILED for a wedged-but-not-failing engine to
    # many minutes); after the first result, error report, or move, the
    # plain straggler timeout applies.
    first_compile_grace_s = 150.0

    def monitor_stragglers_once(self) -> int:
        """Re-dispatch tasks stuck past the straggler timeout (stretched
        to ``first_compile_grace_s`` for never-moved tasks of models with
        no completed task yet — every worker is cold-compiling, not
        stuck); returns how many moved. A task past the retry cap is
        marked permanently FAILED (deterministic failures must not bounce
        between workers forever); pollers see it via `query_failed`."""
        if not self.membership.is_acting_master:
            return 0
        alive = self._eligible_workers()     # one snapshot for the pass
        moved = 0
        now = self.clock()
        for task in self.scheduler.stragglers():
            # atomic snapshot of the assignment this suspicion is about;
            # _redispatch_or_fail drops the move if the book moved on
            worker, stamp, state = self.scheduler.book.assignment(task)
            if state != WORKING:
                continue
            # cumulative counters, not the windowed average: a warm model
            # idle past the metrics window must NOT regain compile grace,
            # and a model with reported engine FAILURES isn't compiling
            if (task.moves == 0 and task.retries == 0
                    and self.metrics.finished_images(task.model) == 0
                    and not self._task_errors.get(task.model)
                    and now - stamp <= self.first_compile_grace_s):
                continue      # cold model, every worker compiling: wait
            if self._redispatch_or_fail(task, "straggler",
                                        snapshot=(worker, stamp),
                                        alive=alive):
                moved += 1
        # gray-failure early pass (ISSUE 20): a task whose worker the
        # differential-health ledger holds SUSPECT or QUARANTINED (slow
        # but heartbeat-alive — the full timeout would wait out a limp
        # that heartbeats never surface) re-dispatches after
        # straggler_early_frac of the window, onto a healthy worker when
        # one exists. Same snapshot/retry-cap semantics as the full pass.
        health = getattr(self.membership, "health", None)
        unhealthy = health.unhealthy() if health is not None else set()
        if unhealthy:
            early_s = (self.config.straggler_timeout_s
                       * self.config.straggler_early_frac)
            healthy_alive = [w for w in alive
                             if w not in unhealthy] or alive
            for task in self.scheduler.book.stragglers(now, early_s):
                worker, stamp, state = self.scheduler.book.assignment(task)
                if state != WORKING or worker not in unhealthy:
                    continue
                if (task.moves == 0 and task.retries == 0
                        and self.metrics.finished_images(task.model) == 0
                        and not self._task_errors.get(task.model)
                        and now - stamp <= self.first_compile_grace_s):
                    continue
                if self._redispatch_or_fail(task, "gray-straggler",
                                            snapshot=(worker, stamp),
                                            alive=healthy_alive):
                    moved += 1
                    self.metrics.record_counter("early_redispatches")
        return moved

    def _redispatch_or_fail(self, task: Task, why: str,
                            snapshot: tuple[str, float],
                            alive: list[str] | None = None) -> bool:
        """Shared failure semantics for the straggler monitor and worker
        error reports: move the task (consuming its retry budget) or,
        past ``max_task_retries``, mark it permanently FAILED. Returns
        True when the task moved. ``snapshot`` is the (worker, stamp)
        assignment the caller's suspicion is ABOUT — required, captured
        where the suspicion arose, so the check spans the caller's whole
        decision window — if the book has moved the task since
        (concurrent member-change reassignment or a racing report), the
        suspicion is stale and the move is dropped: the re-booking thread
        owns the dispatch, and a double move would burn the retry budget
        twice and execute the task on two workers."""
        exp_worker, exp_stamp = snapshot
        cur_worker, cur_stamp, cur_state = \
            self.scheduler.book.assignment(task)
        if (cur_state != WORKING or cur_worker != exp_worker
                or abs(cur_stamp - exp_stamp) > 1e-6):
            return False
        if task.retries >= self.config.max_task_retries:
            # (a move between the check above and here would mislabel the
            # moved task FAILED — the window is lock-free microseconds,
            # vs. the RPC-length window the snapshot check closes)
            self.scheduler.book.mark_failed(task, self.clock())
            import logging
            logging.getLogger("idunno.serving").error(
                "task %s#%s [%s, %s] FAILED after %d re-dispatches "
                "(last worker %s; %s)", task.model, task.qnum, task.start,
                task.end, task.retries, task.worker, why)
            return False
        moved = self.scheduler.redispatch_straggler(
            task, alive if alive is not None else self._eligible_workers(),
            expected_worker=exp_worker, expected_stamp=exp_stamp)
        if moved is None:
            return False              # re-booked mid-decision: not ours
        self._dispatch(moved)
        return True

    # ------------------------------------------------------------------ #
    # worker side
    # ------------------------------------------------------------------ #

    def pending_jobs(self) -> int:
        with self._jobs_lock:
            return len(self._jobs)

    def process_jobs_once(self) -> int:
        """Retry undelivered results, then execute every queued job on the
        local engine; returns the number of jobs executed."""
        with self._jobs_lock:
            retries, self._pending_results = self._pending_results, []
            jobs, self._jobs = self._jobs, []
            self._jobs_available.clear()
        for msg in retries:          # re-send only, never re-compute
            self._deliver_result(msg)
        for job in jobs:
            self._execute(job)
        return len(jobs)

    def wait_for_jobs(self, timeout: float) -> bool:
        return self._jobs_available.wait(timeout)

    def _execute(self, job: Job) -> None:
        t0 = self.clock()
        traced = self.spans is not None and job.trace is not None
        ts0 = self.spans.clock() if traced else 0.0
        try:
            res = self.engine.infer(
                job.model, job.start, job.end,
                dataset_root=job.dataset or self.dataset_root)
        except Exception as e:  # noqa: BLE001 - a bad job must not kill
            # the worker: an engine failure (unfetchable dataset, bad model
            # name, device error) is REPORTED to the master, which
            # re-dispatches immediately (no straggler-timeout wait) and
            # counts it as evidence the model isn't merely compiling
            # (the cold-model grace must not shield deterministic
            # failures). The worker keeps serving its queue.
            import logging
            logging.getLogger("idunno.serving").warning(
                "job %s#%s [%s, %s] failed on %s (%s: %s); reporting to "
                "master for re-dispatch", job.model, job.qnum, job.start,
                job.end, self.host, type(e).__name__, e)
            err_payload = {"model": job.model, "qnum": job.qnum,
                           "start": job.start, "end": job.end,
                           "assigned": job.assigned,
                           "error": f"{type(e).__name__}: {e}"}
            if traced:
                wsp = self.spans.record(
                    "cnn.worker", trace=job.trace[0], parent=job.trace[1],
                    t_start=ts0,
                    attrs={"model": job.model, "qnum": job.qnum,
                           "start": job.start, "end": job.end,
                           "error": f"{type(e).__name__}: {e}"[:120]})
                stamp_trace(err_payload, (job.trace[0], wsp.span_id))
            self._deliver_result(Message(
                MessageType.RESULT, self.host, err_payload))
            return
        elapsed = getattr(res, "elapsed_s", None)
        if elapsed is None:
            elapsed = self.clock() - t0
        records = getattr(res, "records", res)
        payload = {"model": job.model, "qnum": job.qnum,
                   "start": job.start, "end": job.end,
                   "elapsed_s": elapsed,
                   "weights": getattr(res, "weights", "unknown"),
                   "records": [list(r) for r in records]}
        if traced:
            wsp = self.spans.record(
                "cnn.worker", trace=job.trace[0], parent=job.trace[1],
                t_start=ts0,
                attrs={"model": job.model, "qnum": job.qnum,
                       "start": job.start, "end": job.end,
                       "n": len(payload["records"]),
                       "elapsed_s": round(float(elapsed), 6)})
            # the RESULT carries the worker span as parent so the master's
            # collect span closes the loop under it
            stamp_trace(payload, (job.trace[0], wsp.span_id))
        msg = Message(MessageType.RESULT, self.host, payload)
        self._deliver_result(msg)

    def _deliver_result(self, msg: Message) -> None:
        """Send a computed RESULT to the acting master (standby fallback);
        queue the *message* for retry on failure — the inference itself is
        never re-executed."""
        # stamp OUR fence view per delivery attempt (it may have advanced
        # since the job executed): a deposed master receiving it observes
        # the higher epoch and steps down
        msg.payload["epoch"] = list(self.membership.epoch.view())
        master = self.membership.acting_master()
        targets = [master]
        if self.config.standby_coordinator not in targets:
            targets.append(self.config.standby_coordinator)
        for target in targets:
            if target == self.host:
                out = self._handle_result(RESULT_SERVICE, msg)
            else:
                try:
                    out = self.transport.call(target, RESULT_SERVICE, msg,
                                              timeout=30.0)
                except TransportError:
                    continue
            if out is not None and out.type is MessageType.ACK:
                return
        with self._jobs_lock:
            self._pending_results.append(msg)
            self._jobs_available.set()
