"""Cluster-wide prefix cache: content-addressed KV chains on the SDFS
ring (ISSUE 17).

The per-`DecodeServer` radix cache (`serve/prefix_cache.py`) dies with
its pool: behind an autoscaled group every replica re-prefills the same
system prompts, and a freshly spawned replica starts cold exactly when
the group is under SLO pressure. This subsystem publishes hot,
block-aligned prefix chains into SDFS (one `store/kv_chain.py` KVC1
blob per block, placed by the EXISTING ring — no new replication
machinery) and lets any replica's admission path extend a short local
hit with the published suffix.

Flow (all hooks live in `engine/serve_lm.py` / `serve/control.py`):

  publish — after `_finish_admission` inserts a request's chain into
      the radix tree, chains whose admission hit proves sharing
      (local hit >= ``publish_min_hits`` blocks; 0 = always) are
      pushed: blob names are the rolling chunk hash, so identical
      prefixes from any replica/pool converge on identical names and
      a duplicate publish is a version bump of identical bytes
      (the natural-idempotency story for ``prefix_publish``).
  probe — on admission, when the local radix hit is shorter than the
      block-aligned prompt, the prober derives every candidate name
      from its OWN tokens and STATs deepest-first; the first hit is
      the longest published chain sharing the prefix. No directory.
  fetch — `get_bytes` ONLY the missing depths (local_blocks..found),
      verify each blob's embedded chunk tokens, and graft into the
      radix tree (`RadixPrefixCache.graft`); the admission then
      prefills just the remainder — token-exact because grafted KV
      sits at the same absolute positions causal attention demands.
  warm — `lm_manager.group_spawn` sends ``prefix_fetch`` with a
      tenant; the per-tenant warm index (an SDFS JSON blob) maps the
      tenant to its published prefixes so a new replica's first
      request prefills only the suffix.

Staleness: eviction is `store.delete` (an SDFS tombstone); a republish
bumps the version PAST the tombstone (`store/sdfs.py:_master_put`), and
internal ring PUTs refuse zombie versions — so a fetched blob is always
the newest published content or a typed miss, never a resurrected old
chain. On top of that, `decode_block(expect_tokens=...)` refuses any
blob whose embedded chunk differs from the prober's prefix.

Failure policy: probe/fetch/publish NEVER fail serving — every store
or transport error degrades to a miss/skip and bumps ``errors``.

Determinism: no clocks, no rng; the only state is bounded memo dicts.
"""
from __future__ import annotations

import json
from typing import Any, Callable

from idunno_tpu.comm.transport import TransportError
from idunno_tpu.store.kv_chain import (chain_names, decode_block,
                                       encode_block, namespace_key,
                                       tenant_index_name)
from idunno_tpu.store.sdfs import StoreError

# per-tenant warm index caps: entries per tenant, chain depth per entry
_INDEX_ENTRIES = 32
_NOTE_CAP = 256

_MISS = (StoreError, TransportError, OSError, ValueError, KeyError)


def pool_namespace(model, params, prefix_tokens, quantize: str | None,
                   block_size: int, extra: str | None = None) -> str:
    """Namespace id folding in everything that affects KV content: two
    pools share chains ONLY when their model config, a params
    fingerprint (first floats of a few leaves — cheap, order-stable),
    static pool prefix, quantize mode and block_size all agree."""
    import jax
    import numpy as np
    fp = []
    leaves = jax.tree_util.tree_leaves(params)
    for leaf in leaves[:4]:
        flat = np.asarray(jax.device_get(leaf)).reshape(-1)[:64]
        fp.append(np.asarray(flat, np.float32).tobytes().hex())
    cfg = {k: v for k, v in sorted(vars(model).items())
           if isinstance(v, (int, float, str, bool, type(None)))}
    return namespace_key({
        "config": cfg, "params_fp": fp, "n_leaves": len(leaves),
        "prefix": [int(t) for t in (prefix_tokens or ())],
        "quantize": quantize or "", "block_size": int(block_size),
        "extra": extra or ""})


class ClusterPrefixCache:
    """Publish/probe/fetch client for ONE pool (one namespace), bound
    to the node's `FileStoreService`. Thread-safety matches its owner:
    all calls arrive on the pool's serving-loop thread
    (`serve/lm_pool.py` marshals the control verbs there)."""

    def __init__(self, store, namespace: str, block_size: int,
                 publish_min_hits: int = 1) -> None:
        self.store = store
        self.namespace = namespace
        self.block_size = int(block_size)
        # publish only chains whose admission hit had >= this many local
        # blocks (the prompt PROVED it is shared); 0 publishes every
        # inserted chain (the warm path and tests use 0)
        self.publish_min_hits = int(publish_min_hits)
        # names this pool already confirmed published (memo: skip the
        # stat/put); bounded by insertion order
        self._published: dict[str, bool] = {}
        # head-chunk key -> tenant, so a publish triggered deep in the
        # admission path can attribute the chain to the submitting
        # tenant (serve/lm_pool.py notes it at submit time)
        self._tenant_notes: dict[tuple[int, ...], str] = {}
        # counters surfaced as lm_stats gauges (engine/serve_lm.py
        # prefix_cache_stats); warmup() resets via reset_counters()
        self.remote_hits = 0
        self.published_chains = 0
        self.published_blocks = 0
        self.warm_blocks = 0
        self.fetch_bytes = 0
        self.errors = 0

    def reset_counters(self) -> None:
        self.remote_hits = 0
        self.published_chains = 0
        self.published_blocks = 0
        self.warm_blocks = 0
        self.fetch_bytes = 0
        self.errors = 0

    # -- naming ------------------------------------------------------------

    def names(self, tokens: list[int]) -> list[str]:
        return chain_names(self.namespace, tokens, self.block_size)

    def _chunk(self, tokens: list[int], j: int) -> list[int]:
        bs = self.block_size
        return [int(t) for t in tokens[j * bs:(j + 1) * bs]]

    # -- probe -------------------------------------------------------------

    def probe(self, tokens: list[int], start_depth: int = 0) -> int:
        """Deepest published depth (in blocks) for this prefix, probing
        deepest-first via ring `stat` and stopping at the first hit; 0
        when nothing deeper than ``start_depth`` is published. Pure
        read — mutates nothing anywhere."""
        names = self.names(tokens)
        for depth in range(len(names), start_depth, -1):
            name = names[depth - 1]
            try:
                if name in self._published:
                    return depth
                self.store.stat(name)
            except StoreError:
                continue
            except _MISS:
                self.errors += 1
                return 0
            self._memo(name)
            return depth
        return 0

    # -- fetch -------------------------------------------------------------

    def fetch(self, tokens: list[int], from_depth: int, to_depth: int,
              ) -> list[tuple[list[int], dict[str, Any]]]:
        """Blobs for depths [from_depth, to_depth), shallowest first,
        each verified against the expected chunk tokens. Stops at the
        first failure — a chain is only usable as a CONTIGUOUS prefix,
        so a gap ends the fetch (the caller grafts what arrived)."""
        names = self.names(tokens)
        out = []
        for depth in range(from_depth, min(to_depth, len(names))):
            chunk = self._chunk(tokens, depth)
            try:
                blob, _version = self.store.get_bytes(names[depth])
                _meta, arrays = decode_block(blob, expect_tokens=chunk)
            except StoreError:
                break
            except _MISS:
                self.errors += 1
                break
            self.fetch_bytes += len(blob)
            self._memo(names[depth])
            out.append((chunk, arrays))
        return out

    # -- publish -----------------------------------------------------------

    def publish(self, tokens: list[int], n_blocks: int,
                read_block: Callable[[int], dict[str, Any]],
                tenant: str | None = None,
                force: bool = False) -> dict[str, int]:
        """Publish the first ``n_blocks`` full chunks of ``tokens``:
        for each depth whose content-addressed name is not already on
        the ring, encode the pool block (``read_block(j)`` returns the
        raw leaf arrays) and PUT it. Returns {published, blocks}.
        Content addressing via ``chain_names`` is what makes a replayed
        publish converge: same prefix, same names, same bytes.
        ``force`` skips the local published-memo (NOT the ring stat):
        the explicit `prefix_publish` verb uses it so a republish after
        ANOTHER pool's eviction — which this pool's memo cannot see —
        still lands."""
        names = self.names(tokens)[:n_blocks]
        wrote = 0
        for j, name in enumerate(names):
            if not force and name in self._published:
                continue
            try:
                self.store.stat(name)
                self._memo(name)
                continue
            except StoreError:
                pass                            # not published yet
            except _MISS:
                self.errors += 1
                break
            chunk = self._chunk(tokens, j)
            meta = {"tokens": chunk, "depth": j,
                    "namespace": self.namespace,
                    "block_size": self.block_size}
            try:
                blob = encode_block(meta, read_block(j))
                self.store.put_bytes(name, blob)
            except _MISS:
                self.errors += 1
                break
            self._memo(name)
            wrote += 1
        if wrote:
            self.published_chains += 1
            self.published_blocks += wrote
            ten = tenant or self._tenant_notes.get(
                tuple(tokens[:self.block_size]))
            if ten is not None:
                self._index_add(ten, tokens, len(names))
        return {"published": wrote, "blocks": len(names)}

    # -- eviction ----------------------------------------------------------

    def evict(self, tokens: list[int], from_depth: int = 0) -> int:
        """Tombstone every published blob of this chain at depth >=
        ``from_depth``. SDFS versioning makes this safe against
        republish races: a later publish bumps the version past the
        tombstone, and ring-internal PUTs refuse zombie versions — a
        reader never sees the evicted content again."""
        dropped = 0
        for name in self.names(tokens)[from_depth:]:
            try:
                self.store.delete(name)
                dropped += 1
            except _MISS:
                self.errors += 1
            self._published.pop(name, None)
        return dropped

    # -- tenant warm index -------------------------------------------------

    def note(self, tokens: list[int], tenant: str) -> None:
        """Remember which tenant submitted this prompt head, so the
        publish deep in the admission path can attribute the chain.
        Bounded FIFO."""
        if len(tokens) < self.block_size:
            return
        key = tuple(int(t) for t in tokens[:self.block_size])
        self._tenant_notes.pop(key, None)
        self._tenant_notes[key] = str(tenant)
        while len(self._tenant_notes) > _NOTE_CAP:
            self._tenant_notes.pop(next(iter(self._tenant_notes)))

    def _index_add(self, tenant: str, tokens: list[int],
                   depth: int) -> None:
        """Merge (tokens[:depth*bs], depth) into the tenant's warm
        index blob — read-modify-write keeping the LONGEST chain per
        distinct head and at most ``_INDEX_ENTRIES`` entries (newest
        kept)."""
        head = [int(t) for t in tokens[:depth * self.block_size]]
        entries = self.tenant_entries(tenant)
        kept = []
        for e in entries:
            et = e.get("tokens", [])
            if (et[:len(head)] == head or head[:len(et)] == et):
                if len(et) >= len(head):
                    return              # an equal-or-longer chain exists
                continue                # superseded by the new entry
            kept.append(e)
        kept.append({"tokens": head, "depth": int(depth)})
        kept = kept[-_INDEX_ENTRIES:]
        try:
            self.store.put_bytes(
                tenant_index_name(self.namespace, tenant),
                json.dumps({"entries": kept}, sort_keys=True).encode())
        except _MISS:
            self.errors += 1

    def tenant_entries(self, tenant: str) -> list[dict[str, Any]]:
        try:
            blob, _ = self.store.get_bytes(
                tenant_index_name(self.namespace, tenant))
            return list(json.loads(blob.decode()).get("entries", []))
        except _MISS:
            return []

    # -- internals ---------------------------------------------------------

    def _memo(self, name: str) -> None:
        self._published.pop(name, None)
        self._published[name] = True
        while len(self._published) > 4 * _NOTE_CAP:
            self._published.pop(next(iter(self._published)))

    def stats(self) -> dict[str, int]:
        return {"prefix_remote_hits": self.remote_hits,
                "prefix_published_chains": self.published_chains,
                "prefix_published_blocks": self.published_blocks,
                "prefix_warm_blocks": self.warm_blocks,
                "prefix_fetch_bytes": self.fetch_bytes,
                "prefix_store_errors": self.errors}
