from idunno_tpu.serve.metrics import MetricsTracker  # noqa: F401
from idunno_tpu.serve.inference_service import InferenceService  # noqa: F401
