"""Remote control/status RPC — drive a node from OUTSIDE its process.

The reference is driven only by a human typing into each VM's interactive
shell (`mp4_machinelearning.py:1111-1229`); there is no way to script the
cluster from another process. This service exposes the same verb surface
over the typed transport, so deployment tooling, integration tests and a
remote CLI can run the shell's commands against any node:

  status                     — membership view, acting master, loaded models
  put/get/ls/store/delete    — the SDFS verbs (C4) executed by this node
  inference                  — submit a query range (paced chunking like the
                               shell's `inference` verb, C11)
  query_done / results       — poll completion and fetch accumulated records
                               (the master's c4 view, C9/C12)
  stats / grep               — remote c1/c2 percentiles; distributed log grep
  generate                   — one-shot batch decode of a store-persisted LM
  lm_serve/lm_submit/lm_poll/lm_stop
                             — continuous-batching decode pool per LM
                               (engine/serve_lm.py via serve/lm_pool.py)
  lm_qos                     — QoS gateway observability (queue depths,
                               admit/shed counters, queue-wait
                               percentiles; serve/gateway.py). For a
                               replica group, includes the group block
                               (policy, replica roles/states, recent
                               scaling decisions)
  lm_autoscale               — replica-group scaling policy get/set
                               (serve/autoscaler.py; acting master)
  train_start/train_status/train_stop
                             — background cluster training jobs
                               (engine/train_job.py; checkpoints + servable
                               LM published into the replicated store)

One request/one reply on the existing node transport; `comm.net.oneshot_call`
is the matching client side (no listener needed).
"""
from __future__ import annotations

import os
from typing import TYPE_CHECKING

from idunno_tpu.comm.message import Message
from idunno_tpu.comm.transport import TransportError
from idunno_tpu.membership.epoch import (ScopeOwnerRedirect, check_payload,
                                         check_scoped, observe_payload,
                                         place_scope, pool_scope)
from idunno_tpu.utils.spans import stamp_trace, trace_from_payload
from idunno_tpu.utils.types import MessageType

if TYPE_CHECKING:                                    # pragma: no cover
    from idunno_tpu.serve.node import Node

SERVICE = "control"


class RelayedError(Exception):
    """An ERROR reply from a forwarded owner hop, relayed VERBATIM (ISSUE
    16): the payload keeps its typed markers (``stale_epoch``, ``scope``,
    ``scope_owner``, ``scope_epoch``) so a client behind the proxy hop
    still sees the typed error — its retry/re-route logic must not be
    blinded by a flattened string."""

    def __init__(self, payload: dict) -> None:
        super().__init__(payload.get("error", "relayed error"))
        self.payload = dict(payload)


class _Starting:
    """Registry placeholder while an `lm_serve` builds its pool outside the
    lock — reserves the name without blocking other verbs."""


class ControlService:
    def __init__(self, node: "Node") -> None:
        import threading

        self.node = node
        self._lms: dict = {}          # name -> (model, params), loaded once
        self._lm_loops: dict = {}     # name -> LMServingLoop (continuous)
        self._train_jobs: dict = {}   # name -> LMTrainJob
        # (name, idem key) -> node-local row id: dedupes a manager's
        # RE-forward of an lm_submit whose ACK was lost, so the retried
        # request decodes exactly once on this node. Purged per name on
        # lm_serve rebuild / lm_stop — after a rebuild the old row ids
        # are dead, replaying them would map retries onto a new loop's
        # unrelated rows
        self._lm_idem: dict = {}
        # transports run one handler thread per connection: registry
        # check-then-act must be atomic or two concurrent lm_serve/
        # train_start calls each spawn a loop and one leaks unjoinable
        self._reg_lock = threading.Lock()
        node.transport.serve(SERVICE, self._handle)

    def close(self) -> None:
        with self._reg_lock:
            loops = list(self._lm_loops.values())
            self._lm_loops.clear()
            jobs = list(self._train_jobs.values())
            self._train_jobs.clear()
        for loop in loops:
            if not isinstance(loop, _Starting):
                loop.stop()
        for job in jobs:
            job.stop()

    def _handle(self, service: str, msg: Message) -> Message:
        # epoch fence (membership/epoch.py): control verbs stamped by a
        # deposed coordinator are rejected with a typed stale-epoch ERROR
        # before they can mutate anything; unstamped payloads (clients,
        # pre-failover traffic) pass and current stamps advance the local
        # high-water mark
        stale = check_payload(self.node.membership.epoch, msg.payload,
                              self.node.host)
        if stale is not None:
            # ISSUE 6 satellite: PR 5 logged these, now they count
            self.node.metrics.record_counter("stale_epoch_rejected")
            return stale
        # per-pool fence (ISSUE 14): a verb stamped by a deposed POOL
        # owner is rejected for that scope only — the cluster fence above
        # is untouched, so the sender steps down per pool, not globally
        stale = check_scoped(self.node.membership.scopes, msg.payload,
                             self.node.host)
        if stale is not None:
            self.node.metrics.record_counter("stale_scope_rejected")
            return stale
        try:
            out = self._dispatch(msg.payload.get("verb", ""), msg.payload)
            return Message(MessageType.ACK, self.node.host, out)
        except ScopeOwnerRedirect as e:
            # typed not-owner redirect (ISSUE 15): the reply names the
            # scope's owner so the CLIENT re-sends there directly — one
            # hop, counted; server-side forwarding already absorbed the
            # common case, this is the loop-stop for a stale owner map
            self.node.metrics.record_counter("scope_owner_redirects")
            return Message(MessageType.ERROR, self.node.host,
                           {"error": str(e), "scope": e.scope,
                            "scope_owner": e.owner})
        except RelayedError as e:
            # forwarded owner answered with a typed error: pass the
            # payload through untouched so markers survive the hop
            return Message(MessageType.ERROR, self.node.host, e.payload)
        except Exception as e:  # noqa: BLE001 - RPC boundary: report, don't die
            return Message(MessageType.ERROR, self.node.host,
                           {"error": f"{type(e).__name__}: {e}"})

    def _dispatch(self, verb: str, p: dict) -> dict:
        node = self.node
        routed = self._route_cluster(verb, p)
        if routed is not None:
            return routed
        if verb == "status":
            members = {e.host: e.status.value
                       for e in node.membership.members.entries()}
            return {"host": node.host,
                    "acting_master": node.membership.acting_master(),
                    "fence": list(node.membership.epoch.view()),
                    "counters": node.metrics.counters(),
                    "members": members,
                    "models": node.engine.loaded_models()
                    if hasattr(node.engine, "loaded_models") else []}
        if verb == "put":
            version = node.store.put(p["local"], p["name"])
            return {"version": version}
        if verb == "put_bytes":
            version = node.store.put_bytes(
                p["name"], p["data"].encode("latin-1"))
            return {"version": version}
        if verb == "get":
            version = node.store.get(p["name"], p["local"])
            return {"version": version,
                    "size": os.path.getsize(p["local"])}
        if verb == "get_bytes":
            blob, version = node.store.get_bytes(p["name"])
            return {"version": version, "data": blob.decode("latin-1")}
        if verb == "ls":
            return {"hosts": node.store.ls(p["name"])}
        if verb == "store":
            return {"files": node.store.local_files()}
        if verb == "delete":
            node.store.delete(p["name"])
            return {}
        if verb == "inference":
            qnums = node.inference.inference(
                p["model"], int(p["start"]), int(p["end"]),
                pace_s=float(p.get("pace_s", 0.0)),
                dataset=p.get("dataset"))
            return {"qnums": qnums}
        if verb == "query_done":
            return {"done": node.inference.query_done(p["model"],
                                                      int(p["qnum"])),
                    "failed": node.inference.query_failed(p["model"],
                                                          int(p["qnum"]))}
        if verb == "results":
            recs = node.inference.results(p["model"], int(p["qnum"]))
            return {"records": [list(r) for r in recs],
                    "weights": node.inference.weights_provenance()}
        if verb == "stats":
            # remote c1/c2: per-model rates, counts, processing percentiles
            # and the weights-provenance marker
            m = node.metrics
            models = p.get("models")
            if isinstance(models, str):            # scalar like other verbs
                models = [models]
            loaded = getattr(node.engine, "loaded_models", lambda: [])
            provenance = node.inference.weights_provenance()
            out = {}
            for model in (models or node.inference.models_seen()
                          or loaded()):
                ps = m.processing_stats(model)
                out[model] = {
                    "query_rate": m.query_rate(
                        model, node.config.query_batch_size),
                    "image_rate": m.image_rate(model),
                    "finished_images": m.finished_images(model),
                    "finished_queries": m.finished_queries(model),
                    "processing": ps.as_list() if ps else None,
                    "weights": provenance.get(model, "unknown"),
                }
            reply = {"stats": out}
            mgr = getattr(node, "lm_manager", None)
            if mgr is not None and mgr.managed_pools():
                # heterogeneous fair-share arbitration (CNN jobs vs LM
                # pools, measured per-query/per-request rates)
                reply["allocation"] = mgr.allocation_view()
            return reply
        if verb == "grep":
            return {"matches": node.grep.query(p["pattern"])}
        if verb == "generate":
            # serve a store-persisted LM: load once per node (pass
            # reload=true after re-saving a model to refresh the cache),
            # KV-cached decode on every call (engine/generate.py)
            import jax
            import jax.numpy as jnp

            from idunno_tpu.engine.generate import (beam_search, generate,
                                                    load_lm)

            name = p["name"]
            if name not in self._lms or p.get("reload"):
                self._lms[name] = load_lm(node.store, name)
            model, params = self._lms[name]
            prompt = jnp.asarray(p["prompt"], jnp.int32)
            temperature = float(p.get("temperature", 0.0))
            beam_width = int(p.get("beam_width", 0))
            if beam_width >= 1:       # width 1 is valid (greedy + scores)
                # disabled-sampler values (temperature 0, top_p 1, top_k
                # 0) are fine alongside beam; ACTIVE samplers are not
                if (temperature > 0.0 or float(p.get("top_p", 1.0)) < 1.0
                        or int(p.get("top_k", 0)) > 0
                        or float(p.get("presence_penalty", 0.0)) != 0.0
                        or float(p.get("frequency_penalty", 0.0)) != 0.0):
                    raise ValueError("beam_width is a search, not a "
                                     "sampler: temperature/top_p/top_k/"
                                     "penalties don't apply")
                if p.get("prompt_lens") is not None:
                    raise ValueError("beam_search does not support ragged "
                                     "prompt_lens; pad per-call or use "
                                     "the sampler path")
                seqs, scores = beam_search(model, params, prompt,
                                           prompt_len=prompt.shape[1],
                                           max_new=int(p["max_new"]),
                                           beam_width=beam_width)
                return {"tokens": [[int(t) for t in row] for row in seqs],
                        "log_probs": [float(s) for s in scores]}
            kw = {}
            if p.get("prompt_lens") is not None:
                kw["prompt_lens"] = jnp.asarray(p["prompt_lens"])
            if p.get("seed") is not None:
                kw["rng"] = jax.random.PRNGKey(int(p["seed"]))
            elif temperature > 0.0:
                # RPC callers expect varied samples; never fall through to
                # the library's deterministic default key
                import secrets
                kw["rng"] = jax.random.PRNGKey(secrets.randbits(63))
            out = generate(model, params, prompt,
                           prompt_len=prompt.shape[1],
                           max_new=int(p["max_new"]),
                           temperature=temperature,
                           top_p=float(p.get("top_p", 1.0)),
                           top_k=int(p.get("top_k", 0)),
                           # static jit args — distinct values retrace,
                           # same as temperature/top_p/top_k above
                           presence_penalty=float(
                               p.get("presence_penalty", 0.0)),
                           frequency_penalty=float(
                               p.get("frequency_penalty", 0.0)), **kw)
            return {"tokens": [[int(t) for t in row] for row in out]}
        if verb == "lm_serve":
            # continuous-batching serving of a store-persisted LM: a decode
            # pool with `slots` rows; requests stream in via lm_submit and
            # complete independently (engine/serve_lm.py)
            from idunno_tpu.engine.generate import load_lm
            from idunno_tpu.engine.serve_lm import DecodeServer
            from idunno_tpu.serve.lm_pool import LMServingLoop

            name = p["name"]
            # only the registry check-then-act holds the lock; the heavy
            # build (store fetch + device-state allocation) and the old
            # loop's stop() run outside it, behind a reservation
            # placeholder, so other verbs never stall behind a slow serve
            # validate BEFORE touching the registry: a reload request with
            # a bad option must fail without stopping the live loop
            if p.get("kv_cache_dtype") not in (None, "native", "int8"):
                raise ValueError(
                    f"kv_cache_dtype {p['kv_cache_dtype']!r}: "
                    "want native|int8")
            gw_spec = p.get("gateway")
            if gw_spec:
                # same validate-before-registry rule: a bad gateway spec
                # on a reload must not stop the live loop
                from idunno_tpu.serve.gateway import AdmissionGateway
                gw_spec = AdmissionGateway.validate_spec(gw_spec)
            cp_spec = p.get("cluster_prefix") or None
            if cp_spec is not None:
                # cluster prefix cache (ISSUE 17) rides the journaled
                # spec like the block-pool keys; it REQUIRES the radix
                # tier (content is addressed per kv block)
                if not int(p.get("kv_block_size", 0)):
                    raise ValueError(
                        "cluster_prefix needs kv_block_size > 0")
                cp_spec = (dict(cp_spec) if isinstance(cp_spec, dict)
                           else {"on": True})
            placeholder = _Starting()
            with self._reg_lock:
                old = self._lm_loops.get(name)
                if old is not None and (isinstance(old, _Starting)
                                        or not p.get("reload")):
                    return {"already": True}
                self._lm_loops[name] = placeholder
                # new loop generation: the old generation's idempotency
                # row ids are dead, drop them
                for k in [k for k in self._lm_idem if k[0] == name]:
                    del self._lm_idem[k]
            try:
                if old is not None:
                    old.stop()
                # group replicas are named "{group}@r{i}" but load the
                # group's stored model, carried as p["model"]
                model, params = load_lm(node.store,
                                        p.get("model") or name)
                if p.get("kv_cache_dtype"):
                    # serve-time override: e.g. int8 KV residency for a
                    # model stored with a native cache (weights unchanged)
                    import dataclasses as _dc
                    model = _dc.replace(
                        model, kv_cache_dtype=p["kv_cache_dtype"])
                draft = None
                if p.get("draft"):
                    # speculative decoding: the draft is another
                    # store-persisted LM (typically a much smaller one)
                    draft = load_lm(node.store, p["draft"])
                from idunno_tpu.engine.serve_lm import DEFAULT_SLOTS
                server = DecodeServer(
                    model, params,
                    slots=int(p.get("slots", DEFAULT_SLOTS)),
                    prompt_len=int(p["prompt_len"]),
                    max_len=int(p["max_len"]),
                    decode_steps=int(p.get("decode_steps", 1)),
                    quantize=p.get("quantize", "none"),
                    track_logprobs=bool(p.get("track_logprobs", False)),
                    penalties=bool(p.get("penalties", False)),
                    prefix=([int(t) for t in p["prefix"]]
                            if p.get("prefix") else None),
                    eos_id=(int(p["eos_id"])
                            if p.get("eos_id") is not None else None),
                    draft=draft,
                    draft_len=int(p.get("draft_len", 4)),
                    prompt_buckets=(tuple(int(b) for b
                                          in p["prompt_buckets"])
                                    if p.get("prompt_buckets") else None),
                    # paged KV blocks + cross-request radix prefix cache
                    # (0 = off); the keys ride the journaled spec, so a
                    # manager recovery rebuild gets the same pool with an
                    # EMPTY tree — cold misses, never stale KV
                    kv_block_size=int(p.get("kv_block_size", 0)),
                    kv_cache_blocks=int(p.get("kv_cache_blocks", 0)),
                    # block-native paged attention + chunked prefill
                    # (ops/paged_attention.py); both ride the journaled
                    # spec like the block-pool keys above
                    paged_kernel=p.get("paged_kernel"),
                    prefill_chunk=int(p.get("prefill_chunk", 0)),
                    # tensor parallelism over the mesh's "model" axis;
                    # rides the journaled spec so manager placement and
                    # recovery rebuilds keep the same mesh shape
                    n_model=int(p.get("n_model", 1)))
                if p.get("warmup"):
                    # pay the pool's one-time compiles BEFORE the loop
                    # accepts traffic and reset its accounting, so the
                    # first real request's service_s (the fair-share
                    # scheduler's signal, serve/metrics.py) measures
                    # steady-state work, not a compile
                    server.warmup()
                if cp_spec is not None:
                    # attach AFTER warmup: the throwaway warm request
                    # must not publish its chain to the ring. Replicas
                    # of one group (and re-serves of one pool) derive
                    # the SAME namespace from the same model/params/
                    # prefix, so their published chains dedupe; an
                    # explicit "namespace" key pins cross-pool sharing
                    # or isolation by hand.
                    from idunno_tpu.serve.cluster_prefix import (
                        ClusterPrefixCache, pool_namespace)
                    ns = cp_spec.get("namespace") or pool_namespace(
                        server.model, server.params, server.prefix,
                        server.quantize, server.kv_block_size,
                        extra=str(p.get("model") or ""))
                    server.cluster_prefix = ClusterPrefixCache(
                        node.store, ns, server.kv_block_size,
                        publish_min_hits=int(
                            cp_spec.get("publish_min_hits", 1)))
                gateway = None
                if gw_spec is not None:
                    # QoS front door (serve/gateway.py): per-tenant
                    # quotas + priority/deadline queueing + shedding
                    from idunno_tpu.serve.gateway import AdmissionGateway
                    gateway = AdmissionGateway(gw_spec)
                loop = LMServingLoop(server, name=f"{node.host}-{name}",
                                     gateway=gateway,
                                     spans=getattr(node, "spans", None))
            except BaseException:
                with self._reg_lock:
                    if self._lm_loops.get(name) is placeholder:
                        del self._lm_loops[name]
                raise
            with self._reg_lock:
                if self._lm_loops.get(name) is placeholder:
                    self._lm_loops[name] = loop
                    return {"slots": server.slots}
            loop.stop()               # lm_stop won the race mid-build
            return {"stopped": True}
        if verb == "lm_submit":
            from idunno_tpu.serve.admission import AdmissionShed

            # trace context (utils/spans.py): adopt the submitter's stamp
            # (manager forward, traced client) or mint a root here, so
            # every lm_submit is traceable end to end
            spans = getattr(node, "spans", None)
            tctx = trace_from_payload(p)
            key = p.get("idem")
            if key is not None:
                with self._reg_lock:
                    prior = self._lm_idem.get((p["name"], key))
                if prior is not None:
                    if spans is not None and tctx is not None:
                        # dedup made visible in the waterfall: the retried
                        # hop records a span, the request decodes once
                        spans.record("lm.submit", trace=tctx[0],
                                     parent=tctx[1],
                                     attrs={"pool": p["name"], "rid": prior,
                                            "duplicate": True})
                    return {"id": prior, "duplicate": True}
            sp = None
            if spans is not None:
                sp = spans.start("lm.submit",
                                 trace=tctx[0] if tctx else None,
                                 parent=tctx[1] if tctx else None,
                                 attrs={"pool": p["name"]})
            try:
                rid = self._lm_loop(p["name"]).submit(
                    [int(t) for t in p["prompt"]], int(p["max_new"]),
                    temperature=float(p.get("temperature", 0.0)),
                    top_p=float(p.get("top_p", 1.0)),
                    top_k=int(p.get("top_k", 0)),
                    presence_penalty=float(p.get("presence_penalty", 0.0)),
                    frequency_penalty=float(
                        p.get("frequency_penalty", 0.0)),
                    stop=([[int(t) for t in q] for q in p["stop"]]
                          if p.get("stop") else None),
                    seed=(int(p["seed"]) if p.get("seed") is not None
                          else None),
                    # QoS surface (serve/gateway.py): no-ops on pools
                    # without a gateway beyond priority validation
                    tenant=str(p.get("tenant", "default")),
                    priority=str(p.get("priority", "interactive")),
                    deadline_ms=(float(p["deadline_ms"])
                                 if p.get("deadline_ms") is not None
                                 else None),
                    readmit=bool(p.get("readmit")),
                    trace=sp.ctx if sp is not None else None)
            except AdmissionShed as e:
                # ISSUE 6 satellite: per-reason shed counters on the C8
                # tracker (the gateway's own stats stay the pool view)
                node.metrics.record_counter(f"gateway_shed_{e.reason}")
                if sp is not None:
                    spans.finish(sp, shed=e.reason)
                raise
            except Exception:
                if sp is not None:
                    spans.finish(sp, error=True)
                raise
            if sp is not None:
                spans.finish(sp, rid=rid)
            if key is not None:
                with self._reg_lock:
                    if len(self._lm_idem) >= 4096:     # bound the map
                        for k in list(self._lm_idem)[:1024]:
                            del self._lm_idem[k]
                    self._lm_idem[(p["name"], key)] = rid
            return {"id": rid}
        if verb == "lm_poll":
            loop = self._lm_loop(p["name"])
            out = {"completions": [
                {"id": c.id, "tokens": c.tokens, "prompt_len": c.prompt_len,
                 "service_s": round(c.service_s, 6),
                 "cold_start": c.cold_start,
                 "cancelled": c.cancelled,
                 **({"rejected": c.rejected}
                    if c.rejected is not None else {}),
                 **({"logprobs": c.logprobs}
                    if c.logprobs is not None else {})}
                for c in loop.poll()]}
            errs = loop.errors()
            if errs:
                out["errors"] = errs
            return out
        if verb == "lm_cancel":
            # best-effort: True = the cancel was initiated (queued request
            # dropped, or live row retiring with its partial tokens);
            # False = unknown id (already completed or never submitted)
            return {"cancelled":
                    self._lm_loop(p["name"]).cancel(int(p["id"]))}
        if verb == "lm_partial":
            # streaming surface: progress of every live row WITHOUT
            # draining completions (lm_poll keeps that role)
            loop = self._lm_loop(p["name"])
            out = {"partial": loop.snapshot()}
            if loop.gateway is not None:
                # recent gateway rejections with reasons, for lm-tail
                out["sheds"] = loop.gateway.recent_sheds()
            return out
        if verb == "lm_qos":
            # QoS observability: gateway queue depths, admit/shed/expire
            # counters and per-class queue-wait percentiles (None when
            # the pool runs without a gateway)
            gw = self._lm_loop(p["name"]).gateway
            return {"qos": gw.stats() if gw is not None else None}
        if verb in ("prefix_publish", "prefix_probe", "prefix_fetch"):
            # cluster prefix cache (ISSUE 17): publish pushes cached
            # chains to the SDFS ring, probe reports local-vs-published
            # depth (pure read), fetch (the warm-at-spawn primitive)
            # grafts published chains into the pool's radix tree. All
            # three are fenced + scope-stamped like any pool verb (the
            # _handle preamble) and idempotent by content addressing —
            # contract rows in analysis/contracts.py.
            loop = self._lm_loop(p["name"])
            op = verb.split("_", 1)[1]
            kw: dict = {}
            if p.get("tokens") is not None:
                kw["tokens"] = [int(t) for t in p["tokens"]]
            if op != "probe" and p.get("tenant") is not None:
                kw["tenant"] = str(p["tenant"])
            return loop.prefix_op(op, **kw)
        if verb == "kv_handoff":
            # DistServe prefill→decode block handoff (ISSUE 18): fenced +
            # scope-stamped by the _handle preamble like every pool verb,
            # idempotent by radix-graft reuse — contracts.py row
            return self._kv_handoff(p)
        if verb == "lm_stats":
            stats = self._lm_loop(p["name"]).stats()
            # surface pool gauges on the node's C8 metrics tracker so the
            # cluster metrics plane (metrics_export) sees them: tensor-
            # parallel shape + per-step psum payload always, plus the
            # prefix-cache gauges and the paged/chunked win counters when
            # the cache is on (gather traffic avoided, admissions split)
            cfg = stats.get("config", {})
            gauges = {"n_model": cfg.get("n_model", 1),
                      "tp_collective_bytes": cfg.get(
                          "tp_collective_bytes", 0),
                      "sampling_collective_bytes": cfg.get(
                          "sampling_collective_bytes", 0)}
            pc = stats.get("prefix_cache")
            if pc is not None:
                gauges.update(
                    pc,
                    kv_gather_bytes_saved=stats.get(
                        "kv_gather_bytes_saved", 0),
                    prefill_chunks=stats.get("prefill_chunks", 0),
                    # DistServe handoff gauges (ISSUE 18): ships from /
                    # KVC1 bytes through / ships abandoned on this pool
                    kv_handoff_requests=stats.get(
                        "kv_handoff_requests", 0),
                    kv_handoff_bytes=stats.get("kv_handoff_bytes", 0),
                    kv_handoff_fallbacks=stats.get(
                        "kv_handoff_fallbacks", 0))
            node.metrics.record_lm_gauges(p["name"], gauges)
            # ISSUE 20: the node's differential-health verdict summary
            # (worst peer deviation ratio, quarantine count) and the
            # process-wide hedge counters ride every lm_stats reply so
            # `lm-stats` shows the gray-failure picture without a
            # separate scrape
            from idunno_tpu.comm.retry import retry_counters as _rc
            hl = getattr(node.membership, "health", None)
            if hl is not None:
                c = _rc()
                stats["node_health"] = dict(
                    hl.gauges(),
                    hedged_rpcs=c["hedged_rpcs"],
                    hedge_wins=c["hedge_wins"])
            gw = stats.get("gateway")
            if gw is not None:
                node.metrics.record_gateway_gauges(p["name"], {
                    "queued": gw["queued"],
                    **{f"{c}_{k}": cls[k]
                       for c, cls in gw["classes"].items()
                       for k in ("queued", "admitted", "dispatched",
                                 "expired", "reject_rate")},
                    **{f"{c}_wait_{q}": cls["queue_wait_s"][q]
                       for c, cls in gw["classes"].items()
                       for q in ("p50", "p95", "p99")}})
            return {"stats": stats}
        if verb == "lm_stop":
            with self._reg_lock:
                loop = self._lm_loops.pop(p["name"], None)
                for k in [k for k in self._lm_idem
                          if k[0] == p["name"]]:
                    del self._lm_idem[k]
            if loop is not None and not isinstance(loop, _Starting):
                loop.stop()
            # popping a _Starting reservation makes the builder's final
            # registry compare fail, so it stops its fresh loop itself
            return {"stopped": loop is not None}
        if verb == "train_start":
            # cluster training job: corpus from the replicated store,
            # periodic TrainState checkpoints back into it, final servable
            # LM published for lm_serve/generate (engine/train_job.py)
            from idunno_tpu.engine.train_job import LMTrainJob

            name = p["name"]
            with self._reg_lock:
                existing = self._train_jobs.get(name)
                if existing is not None:
                    st = existing.status()
                    if not (st["done"] or st["stopped"] or st["error"]):
                        raise ValueError(f"training job {name!r} already "
                                         "running (train_stop it first)")
                self._train_jobs[name] = LMTrainJob(
                    node.store, name,
                    corpus=p["corpus"],
                    model_config=dict(p["model"]),
                    steps=int(p["steps"]),
                    batch_size=int(p.get("batch_size", 8)),
                    seq_len=int(p.get("seq_len", 32)),
                    lr=float(p.get("lr", 1e-2)),
                    checkpoint_every=int(p.get("checkpoint_every", 50)),
                    seed=int(p.get("seed", 0)),
                    resume=bool(p.get("resume", False)))
            return {"started": True}
        if verb == "profile":
            # capture a jax.profiler trace of whatever this node executes
            # during the window (worker jobs, decode pools) — remote,
            # on-demand observability the reference never had (its only
            # timing is host wall-clock prints, `alexnet_resnet.py:91-92`)
            import time as _time

            from idunno_tpu.utils.tracing import trace

            seconds = float(p.get("seconds", 3.0))
            if not 0.0 < seconds <= 60.0:
                raise ValueError(f"seconds={seconds}: want (0, 60]")
            log_dir = p.get("log_dir") or os.path.join(
                node.store.local.data_dir, "profiles",
                _time.strftime("%Y%m%d-%H%M%S"))
            with trace(log_dir):
                _time.sleep(seconds)
            return {"log_dir": log_dir, "seconds": seconds}
        if verb == "train_status":
            with self._reg_lock:
                job = self._train_jobs.get(p["name"])
            if job is None:
                raise ValueError(f"no training job {p['name']!r}")
            return job.status()
        if verb == "train_stop":
            with self._reg_lock:
                job = self._train_jobs.get(p["name"])
            if job is None:
                return {"stopped": False}
            job.stop()
            # "stopped" = the stop verb found+stopped a job; the job's own
            # lifecycle flags live under "status" (its 'stopped' field is
            # False when the job had already finished)
            return {"stopped": True, "status": job.status()}
        if verb == "spans_dump":
            # node-local span window (utils/spans.py); the cluster-wide
            # view is the `trace` verb below
            spans = getattr(node, "spans", None)
            return {"node": node.host,
                    "spans": ([] if spans is None else spans.dump(
                        trace_id=p.get("trace_id"),
                        limit=(int(p["limit"])
                               if p.get("limit") else None)))}
        if verb == "trace":
            return self._collect_trace(p)
        if verb == "metrics_export":
            # Prometheus text exposition of everything observable on this
            # node: C8 tracker counters/rates/percentiles/gauges plus the
            # process-wide retry counters and span-buffer gauges
            from idunno_tpu.comm.retry import retry_counters

            target = p.get("host")
            if target and target != node.host:
                out = node.transport.call(
                    target, SERVICE,
                    Message(MessageType.INFERENCE, node.host,
                            {"verb": "metrics_export"}), timeout=5.0)
                if out is None or out.type is not MessageType.ACK:
                    raise ValueError(f"metrics_export: {target} unreachable")
                return {"text": out.payload["text"]}
            spans = getattr(node, "spans", None)
            extra_g = {}
            if spans is not None:
                extra_g["span_buffer_depth"] = spans.depth()
                extra_g["spans_recorded_total"] = spans.recorded_total()
            fo = getattr(node, "failover", None)
            if fo is not None:
                # ISSUE 14 satellite: the PR-5 durability-gap counter
                # (acked work whose write-ahead was skipped because the
                # standby was down) joins the scrape; the per-pool
                # adoption/replay counters ride the tracker's
                # record_counter events automatically
                extra_g["wal_skips"] = fo.wal_skips
                # ISSUE 15 satellite: cumulative bytes shipped over the
                # per-pool WAL (delta frames + full fallbacks) — the
                # number the delta compaction is supposed to shrink
                extra_g["pool_wal_bytes"] = fo.pool_wal_bytes()
            lmgr = getattr(node, "lm_manager", None)
            if lmgr is not None:
                # ISSUE 17 satellite: journal rows compacted out of
                # shipped per-pool WAL segments below the delivered
                # low-water mark
                extra_g["pool_wal_truncated"] = lmgr.wal_truncated
            # ISSUE 15: ownership-routing counters are always present in
            # the scrape (zero until the first redirect/handoff) so
            # dashboards can alert on them without a priming event
            # ISSUE 20: node_health_score (worst peer deviation ratio)
            # and quarantined_nodes from the differential ledger; the
            # hedge counters ride retry_counters() below
            hl = getattr(node.membership, "health", None)
            if hl is not None:
                extra_g.update(hl.gauges())
            extra_c = dict(retry_counters())
            cc = node.metrics.counters()
            # ISSUE 18/20: handoff-fallback, predictive-spawn and
            # gray-failure routing counters join the always-present set
            # (zero until the first event)
            for k in ("scope_owner_redirects", "scope_owner_moves",
                      "kv_handoff_fallbacks", "predictive_spawns",
                      "early_redispatches", "quarantine_reroutes"):
                extra_c.setdefault(k, cc.get(k, 0))
            return {"text": node.metrics.prometheus_text(
                node.host, extra_counters=extra_c,
                extra_gauges=extra_g)}
        if verb == "lm_autoscale":
            # only meaningful for a manager-owned replica group (routed
            # above); reaching here means the name isn't one
            raise ValueError(
                f"no replica group {p.get('name')!r}; lm_serve with "
                "autoscale={...} (placement=auto) creates one")
        raise ValueError(f"unknown control verb {verb!r}")

    def _collect_trace(self, p: dict) -> dict:
        """Cluster-wide trace collection: resolve the trace id (given
        directly, or looked up from an LM pool request id / CNN qnum),
        then fan `spans_dump` out to every alive member and merge the
        returned spans sorted by start time — the shell waterfall and
        `tools/trace_export.py` both consume this."""
        node = self.node
        tid = p.get("trace_id")
        if tid is None and p.get("name") is not None \
                and p.get("id") is not None:
            name, rid = p["name"], int(p["id"])
            mgr = getattr(node, "lm_manager", None)
            if mgr is not None and mgr.has_pool(name) \
                    and not p.get("local"):
                tid = mgr.trace_of(name, rid)
            else:
                with self._reg_lock:
                    loop = self._lm_loops.get(name)
                if loop is not None and not isinstance(loop, _Starting):
                    tid = loop.trace_of(rid)
        if tid is None and p.get("model") is not None \
                and p.get("qnum") is not None:
            tid = node.inference.trace_of(p["model"], int(p["qnum"]))
        if tid is None:
            raise ValueError(
                "trace: pass trace_id, or name+id for an LM request, or "
                "model+qnum for a CNN query (unknown/untraced ids "
                "resolve to nothing)")
        merged: list[dict] = []
        nodes: list[str] = []
        ask = {"verb": "spans_dump", "trace_id": tid, "local": True}
        for h in node.membership.members.alive_hosts():
            if h == node.host:
                spans = getattr(node, "spans", None)
                got = [] if spans is None else spans.dump(trace_id=tid)
            else:
                try:
                    out = node.transport.call(
                        h, SERVICE, Message(MessageType.INFERENCE,
                                            node.host, dict(ask)),
                        timeout=5.0)
                except Exception:  # noqa: BLE001 - best-effort collection
                    continue
                if out is None or out.type is not MessageType.ACK:
                    continue
                got = out.payload.get("spans", [])
            if got:
                nodes.append(h)
                merged.extend(got)
        merged.sort(key=lambda s: (s.get("t_start", 0.0), s["span_id"]))
        return {"trace_id": tid, "spans": merged, "nodes": nodes}

    def _kv_handoff(self, p: dict) -> dict:
        """DistServe KV-block handoff (ISSUE 18). Node-local ops ("probe"
        | "export" | "adopt" | "fallback") marshal onto the named pool's
        loop thread; op="ship" ORCHESTRATES from the prefill pool's node:
        probe the decode target for its already-held depth, export only
        the missing block suffix as KVC1 blobs (`store/kv_chain.py`
        codec), and push them point-to-point to the target's adopt — no
        SDFS round-trip on the critical path. KVC1 blobs ride the RPC
        payload as latin-1 strings (the `put_bytes` idiom). Any failure
        after the ship starts bumps the fallback counter on THIS pool and
        re-raises: the caller (lm_manager._handoff_ship) falls back to
        decode-side prefill — a handoff is only ever an optimization,
        never a correctness dependency. Idempotent end to end: export
        reads cached blocks, adopt grafts with reuse-on-existing
        semantics, so a replayed ship converges on the same tree."""
        node = self.node
        op = p.get("op", "")
        loop = self._lm_loop(p["name"])
        toks = ([int(t) for t in p["tokens"]]
                if p.get("tokens") is not None else None)
        spans = getattr(node, "spans", None)
        tctx = trace_from_payload(p)
        tr = tctx if spans is not None else None
        if op == "probe":
            return loop.handoff_op("probe", tokens=toks)
        if op == "export":
            out = loop.handoff_op("export", tokens=toks,
                                  from_depth=int(p.get("from_depth", 0)),
                                  trace=tr)
            out["blobs"] = [b.decode("latin-1") for b in out["blobs"]]
            return out
        if op == "adopt":
            return loop.handoff_op(
                "adopt", tokens=toks,
                blobs=[b.encode("latin-1") for b in p["blobs"]],
                start_depth=int(p.get("start_depth", 0)), trace=tr)
        if op == "fallback":
            return loop.handoff_op("fallback")
        if op != "ship":
            raise ValueError(f"unknown kv_handoff op {op!r}")
        target_host = p["target_host"]
        target_name = p.get("target_name") or p["name"]
        if target_host == node.host and target_name == p["name"]:
            raise ValueError("kv_handoff ship: target is the source pool")
        sp = None
        if spans is not None:
            sp = spans.start("lm.handoff",
                             trace=tctx[0] if tctx else None,
                             parent=tctx[1] if tctx else None,
                             attrs={"pool": p["name"],
                                    "target": target_host,
                                    "target_pool": target_name})
        ctx = sp.ctx if sp is not None else None

        def _call(payload: dict) -> dict:
            # child hops chain under the ship span and carry this node's
            # fence view (the stamp checker's send-site rule)
            stamp_trace(payload, ctx)
            payload["epoch"] = list(node.membership.epoch.view())
            out = node.transport.call(
                target_host, SERVICE,
                Message(MessageType.INFERENCE, node.host, payload),
                timeout=float(p.get("timeout", 30.0)))
            if out is None:
                raise TransportError(
                    f"kv_handoff: {target_host} gave no reply",
                    reason="timeout")
            observe_payload(node.membership.epoch, out.payload)
            if out.type is not MessageType.ACK:
                raise ValueError(str(
                    (out.payload or {}).get("error", "kv_handoff failed")))
            return dict(out.payload or {})

        try:
            probe = _call({"verb": "kv_handoff", "op": "probe",
                           "name": target_name, "tokens": list(toks),
                           "local": True})
            depth = int(probe["depth"])
            export = loop.handoff_op("export", tokens=toks,
                                     from_depth=depth, trace=ctx)
            if export["blocks"] == 0:
                # the target already holds every shippable block — the
                # delta is empty, decode admits with a pure local hit
                if sp is not None:
                    spans.finish(sp, blocks=0, bytes=0, held_depth=depth)
                return {"shipped": 0, "bytes": 0, "depth": depth,
                        "already": True}
            adopt = _call({
                "verb": "kv_handoff", "op": "adopt", "name": target_name,
                "tokens": list(toks),
                "blobs": [b.decode("latin-1") for b in export["blobs"]],
                "start_depth": depth, "local": True})
        except Exception:
            # count the abandoned ship on the PREFILL pool (its blocks
            # were exported for nothing) and on the node tracker for
            # metrics_export; the request itself survives via the
            # caller's decode-side-prefill fallback
            try:
                loop.handoff_op("fallback")
            except Exception:  # noqa: BLE001 - counter must not mask
                pass
            node.metrics.record_counter("kv_handoff_fallbacks")
            if sp is not None:
                spans.finish(sp, error=True)
            raise
        if sp is not None:
            spans.finish(sp, blocks=export["blocks"],
                         bytes=export["bytes"],
                         adopted_depth=adopt.get("depth"))
        return {"shipped": export["blocks"], "bytes": export["bytes"],
                "depth": depth, "adopted": adopt.get("adopted", 0),
                "target_depth": adopt.get("depth")}

    # pool-directed verbs that route by scope owner (ISSUE 15)
    _POOL_VERBS = ("lm_submit", "lm_poll", "lm_stats", "lm_stop",
                   "lm_cancel", "lm_partial", "lm_qos", "lm_autoscale",
                   "prefix_publish", "prefix_probe", "prefix_fetch",
                   "kv_handoff")

    def _forward_scope_owner(self, p: dict, name: str, owner: str) -> dict:
        """Owner-aware routing (ISSUE 15): this node does not hold the
        pool but the gossiped ownership map names an alive owner —
        forward the verb there transparently (ONE hop: the forwarded
        payload carries ``_owner_hop`` so a stale map can never loop)
        and relay the owner's reply. The hop is the counted redirect;
        clients that pre-route by their own owner view skip it."""
        node = self.node
        node.metrics.record_counter("scope_owner_redirects")
        fwd = dict(p, _owner_hop=True,
                   epoch=list(node.membership.epoch.view()))
        try:
            out = node.transport.call(
                owner, SERVICE,
                Message(MessageType.INFERENCE, node.host, fwd),
                timeout=30.0)
        except TransportError as e:
            raise ValueError(f"scope owner {owner} for {name!r} "
                             f"unreachable: {e}") from e
        if out is None:
            raise ValueError(
                f"scope owner {owner} for {name!r} gave no reply")
        observe_payload(node.membership.epoch, out.payload)
        if out.type is MessageType.ERROR:
            # relay the owner's typed error verbatim — flattening it to a
            # string here would strip the stale_epoch/scope/scope_owner
            # markers a chained redirect needs (ISSUE 16 satellite)
            raise RelayedError(dict(out.payload or {}))
        return dict(out.payload or {})

    def _route_cluster(self, verb: str, p: dict) -> dict | None:
        """Cluster-managed LM tier (serve/lm_manager.py): placement verbs
        carry ``placement="auto"`` and MUST land on the acting master
        (which hands each scope to its rendezvous owner); follow-up verbs
        route by SCOPE OWNER — the holder serves them, any other node
        forwards one hop to the gossiped owner, and a deposed holder
        answers with a typed ``ScopeOwnerRedirect``. ``local=True`` (set
        by the manager's own node-to-node RPCs) pins the node-local tier,
        so a managed pool's host still answers the manager. None = not a
        cluster-routed call, fall through."""
        mgr = getattr(self.node, "lm_manager", None)
        if mgr is None or p.get("local"):
            return None
        if verb == "lm_serve" and p.get("placement") == "assign":
            # owner landing of a scope assign hop (pool_assign contract):
            # the acting master placed this scope here — serve it now, no
            # re-forward (assign is a single hop); a replayed assign finds
            # the named pool and absorbs as already=True
            return mgr.serve(p, assigned=True)
        placed = (p.get("placement") == "auto"
                  and verb in ("lm_serve", "train_start"))
        if placed:
            master = self.node.membership.acting_master()
            if master != self.node.host \
                    or not self.node.membership.is_acting_master:
                raise ValueError(
                    f"placement=auto must go to the acting master "
                    f"({master}), not {self.node.host}")
            return (mgr.serve(p) if verb == "lm_serve"
                    else mgr.train(p))
        name = p.get("name")
        if verb in self._POOL_VERBS and not mgr.has_pool(name):
            # not held here: forward one hop to the scope's claimed owner.
            # The claim is trusted even when our liveness view lags (a
            # healed node may observe the claim a wave before the owner's
            # RUNNING refutation) — a genuinely dead owner surfaces as
            # the typed unreachable error, and its successor's fresher
            # claim arrives on the same gossip that revives liveness.
            owners = getattr(self.node.membership, "owners", None)
            if owners is not None and not p.get("_owner_hop"):
                scope = pool_scope(name)
                owner = owners.owner(scope)
                if owner == self.node.host:
                    # our own stale claim (we just stepped this scope
                    # down): guess the successor by rendezvous placement
                    # over the alive view rather than bouncing the client
                    alive = set(
                        self.node.membership.members.alive_hosts())
                    # quarantine-blind: the guess must match the adoption
                    # formula (failover._adopt_scopes_of) — see the
                    # split-brain note there
                    owner = place_scope(
                        scope, self.node.config.hosts, alive)
                if owner is not None and owner != self.node.host:
                    return self._forward_scope_owner(p, name, owner)
            # UNCLAIMED scope (direct pools, bare harnesses, or the
            # pre-gossip window): fall through to the node-local tier —
            # its "no lm_serve pool" error is the pre-ownership behavior
        if verb in self._POOL_VERBS and mgr.has_pool(name):
            owners = getattr(self.node.membership, "owners", None)
            claimed = (owners.owner(pool_scope(name))
                       if owners is not None else None)
            if owners is None or claimed is None:
                # no ownership map (bare harnesses) or an unclaimed
                # scope: the PR-13 rule — only the acting master may
                # serve a managed journal
                if not self.node.membership.is_acting_master:
                    raise ValueError(
                        f"{self.node.host} is not the acting master; its "
                        f"managed journal for {name!r} is fenced")
            elif claimed != self.node.host:
                # deposed holder: the scope's adopter out-claimed us —
                # step down for this scope only and redirect, typed;
                # serving the stale journal would double-deliver
                mgr.step_down_scope(pool_scope(name))
                raise ScopeOwnerRedirect(pool_scope(name), claimed)
            if verb == "lm_submit":
                rid = mgr.submit(name, [int(t) for t in p["prompt"]],
                                 int(p["max_new"]),
                                 top_p=float(p.get("top_p", 1.0)),
                                 top_k=int(p.get("top_k", 0)),
                                 presence_penalty=float(
                                     p.get("presence_penalty", 0.0)),
                                 frequency_penalty=float(
                                     p.get("frequency_penalty", 0.0)),
                                 stop=([[int(t) for t in q]
                                        for q in p["stop"]]
                                       if p.get("stop") else None),
                                 temperature=float(
                                     p.get("temperature", 0.0)),
                                 seed=(int(p["seed"])
                                       if p.get("seed") is not None
                                       else None),
                                 tenant=str(p.get("tenant", "default")),
                                 priority=str(p.get("priority",
                                                    "interactive")),
                                 deadline_ms=(float(p["deadline_ms"])
                                              if p.get("deadline_ms")
                                              is not None else None),
                                 idem_key=p.get("idem"),
                                 trace=trace_from_payload(p))
                return {"id": rid}
            if verb == "lm_poll":
                return mgr.poll(name)
            if verb == "lm_stats":
                return {"stats": mgr.stats(name)}
            if verb == "lm_cancel":
                return mgr.cancel(name, int(p["id"]))
            if verb == "lm_partial":
                return mgr.partial(name)
            if verb == "lm_qos":
                out = mgr.qos(name)
                grp = out.get("group")
                if grp is not None:
                    # autoscaler observability rides the metrics tracker
                    # (Prometheus metrics_export + chaos snapshots)
                    states = [m.get("state") for m
                              in grp.get("replicas", {}).values()]
                    fc = grp.get("forecast") or {}
                    self.node.metrics.record_autoscale_gauges(name, {
                        "replicas": len(states),
                        "draining": states.count("draining"),
                        "decisions_total": grp.get("decisions_total", 0),
                        # predictive scale-ahead view (ISSUE 18)
                        "predicted_rate": fc.get("predicted_rate", 0.0),
                        "predictive_spawns": fc.get(
                            "predictive_spawns", 0)})
                return out
            if verb == "lm_autoscale":
                # policy get/set for a replica group (serve/autoscaler.py)
                if p.get("policy"):
                    return mgr.autoscale_set(name, dict(p["policy"]))
                return mgr.autoscale_get(name)
            if verb in ("prefix_publish", "prefix_probe",
                        "prefix_fetch"):
                # managed pools: relay to the pool's node (or fan over a
                # group's replicas) — prefix state lives on the serving
                # node, the journal only knows the spec
                return mgr.prefix_op(verb, name, p)
            if verb == "kv_handoff":
                # managed pools: relay to the pool's serving node — a
                # ship must orchestrate FROM the prefill replica's own
                # host (its loop owns the exported blocks)
                return mgr.kv_handoff(name, p)
            return mgr.stop(name)
        if verb in ("train_status", "train_stop") and mgr.has_job(name):
            return (mgr.train_status(name) if verb == "train_status"
                    else mgr.train_stop(name))
        return None

    def _lm_loop(self, name: str):
        with self._reg_lock:
            loop = self._lm_loops.get(name)
        if loop is None:
            raise ValueError(f"no lm_serve pool for {name!r}; "
                             "call lm_serve first")
        if isinstance(loop, _Starting):
            raise ValueError(f"lm_serve pool for {name!r} is still "
                             "starting; retry shortly")
        return loop
