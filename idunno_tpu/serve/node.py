"""Node assembly — the per-host runtime (SURVEY.md C15).

The reference's ``Server`` object wires all state in ``__init__``
(`mp4_machinelearning.py:115-160`) and ``run()`` spawns ~13 daemon threads
(`:1270-1334`). Here a ``Node`` composes the layered services over one
transport and runs four periodic loops (heartbeat, failure monitor,
straggler monitor + metadata replication, worker job pump). Loops are
plain-step methods on the services, so tests drive them synchronously and
only the real runtime sleeps.
"""
from __future__ import annotations

import threading
import time

from idunno_tpu.comm.transport import Transport
from idunno_tpu.config import ClusterConfig, EngineConfig
from idunno_tpu.grep.loggrep import LogGrepService
from idunno_tpu.membership.service import MembershipService
from idunno_tpu.serve.control import ControlService
from idunno_tpu.serve.failover import FailoverManager
from idunno_tpu.serve.inference_service import InferenceService
from idunno_tpu.serve.lm_manager import LMPoolManager
from idunno_tpu.serve.metrics import MetricsTracker
from idunno_tpu.store.sdfs import FileStoreService
from idunno_tpu.utils.logging import setup_node_logging
from idunno_tpu.utils.spans import SpanStore


class Node:
    def __init__(self, host: str, config: ClusterConfig,
                 transport: Transport, data_dir: str,
                 engine=None, engine_config: EngineConfig | None = None,
                 dataset_root: str | None = None,
                 log_dir: str | None = None) -> None:
        self.host = host
        self.config = config
        self.transport = transport
        self.log = setup_node_logging(host, log_dir or data_dir)
        # per-node span ring buffer: always on (Dapper-style), bounded
        # memory, read back via the spans_dump / trace / metrics_export
        # verbs (utils/spans.py)
        self.spans = SpanStore(host)
        self.membership = MembershipService(host, config, transport)
        # attach the differential-health ledger to the transport: every
        # reliable call from this node now feeds per-peer latency/error
        # EWMAs (gray-failure defense; membership/health.py)
        transport.health = self.membership.health
        self.store = FileStoreService(host, config, transport,
                                      self.membership, data_dir)
        self.store.spans = self.spans
        if engine is None:
            # deferred import: pure-control-plane nodes shouldn't pay for jax
            from idunno_tpu.engine.inference import InferenceEngine
            engine = InferenceEngine(engine_config or EngineConfig(),
                                     store=self.store)
        self.engine = engine
        self.metrics = MetricsTracker()
        self.inference = InferenceService(host, config, transport,
                                          self.membership, engine,
                                          metrics=self.metrics,
                                          dataset_root=dataset_root)
        self.inference.spans = self.spans
        self.lm_manager = LMPoolManager(host, config, transport,
                                        self.membership, self.inference)
        self.lm_manager.spans = self.spans
        self.failover = FailoverManager(host, config, transport,
                                        self.membership, self.inference,
                                        lm_manager=self.lm_manager)
        # submit-path write-ahead: an acked query survives an immediate
        # coordinator death (see InferenceService._master_submit)
        self.inference.wal_hook = self.failover.wal_append
        # scaling-decision write-ahead: an autoscaler action the master
        # just journaled survives an immediate coordinator death too
        # (serve/lm_manager.py:_replicate_scale → wal_scale)
        self.lm_manager.failover = self.failover
        self.grep = LogGrepService(host, config, transport, self.membership,
                                   log_dir or data_dir)
        self.control = ControlService(self)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self.membership.join()
        loops = [
            ("heartbeat", self._heartbeat_loop),
            ("monitor", self._monitor_loop),
            ("master-duties", self._master_loop),
            ("worker", self._worker_loop),
        ]
        warmup = getattr(getattr(self.engine, "config", None),
                         "warmup_models", ())
        if warmup and hasattr(self.engine, "warmup"):
            loops.append(("warmup", lambda: self._warmup(warmup)))
        for name, fn in loops:
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"{self.host}-{name}")
            t.start()
            self._threads.append(t)
        self.log.info("node %s started", self.host)

    def _warmup(self, models) -> None:
        """Compile the configured models before the first job arrives (the
        worker loop still serves: jobs for a still-compiling model simply
        block on the same jit cache entry)."""
        for name in models:
            if self._stop.is_set():
                return
            try:
                secs = self.engine.warmup(name)
                self.log.info("warmed %s in %.1fs", name, secs)
            except Exception as e:  # noqa: BLE001 - warmup must not kill node
                self.log.warning("warmup %s failed: %s", name, e)

    def stop(self) -> None:
        self._stop.set()
        self.control.close()          # continuous-batching decode loops
        for t in self._threads:
            t.join(timeout=2.0)
        self.transport.close()
        self.log.info("node %s stopped", self.host)

    def leave(self) -> None:
        """Voluntary leave (shell command 4)."""
        self.membership.leave()

    # -- loops ------------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            self.membership.ping_once()
            time.sleep(self.config.ping_interval_s)

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            self.membership.monitor_once()
            time.sleep(self.config.ping_interval_s)

    def _master_loop(self) -> None:
        """Straggler re-dispatch + standby metadata replication, both 1 Hz
        (`:809-830, 971-987`)."""
        while not self._stop.is_set():
            # each duty isolated: one raising must not take down the
            # others (a dead master loop = no straggler re-dispatch, no
            # LM pump, no standby replication — silent loss of the
            # cluster's guarantees)
            for duty in (self.inference.monitor_stragglers_once,
                         self.lm_manager.pump_once,
                         self.failover.replicate_once):
                try:
                    duty()
                except Exception:  # noqa: BLE001 - loop must stay alive
                    self.log.exception("master duty %s failed",
                                       getattr(duty, "__name__", duty))
            time.sleep(self.config.metadata_interval_s)

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            if self.inference.wait_for_jobs(timeout=0.2):
                self.inference.process_jobs_once()
