"""Closed-loop capacity: gauge-driven autoscaler for replica pool groups.

An `lm_serve` spec that carries `autoscale={...}` creates a replica pool
GROUP instead of a single pool (`serve/lm_manager.py`): the group owns a
set of ordinary managed replica pools (`{group}@r{i}`, deterministic
names journaled as `next_replica` — the spawn idempotency backstop,
since `LMPoolManager.serve` answers `{"already": True}` for a name that
exists) and the `Autoscaler` here closes the loop over them from the
acting master's `pump_once`:

  - scale OUT when the interactive p95 queue wait (the gateway's
    Clockwork-style SLO signal, `serve/gateway.py` `queue_wait_s.p95`)
    crosses `deadline_slack_s` — spawning a decode replica, or a
    `prefill_chunk`-tuned PREFILL replica when long-prompt admissions
    dominate (DistServe's prefill/decode split at request-routing
    granularity; Zhong et al., OSDI 2024);
  - scale IN when the signal falls below `scale_in_frac * slack` (or
    the group goes idle): mark the newest replica DRAINING — it takes
    no new routing but keeps delivering — and retire it only once every
    journaled request on it has been DELIVERED and `drain_window_s`
    has elapsed (zero admitted-request loss);
  - REBALANCE tenants across decode replicas by WFQ debt (outstanding
    journal work weighted by 1/tenant-weight) when the debt gap
    exceeds `rebalance_debt`.

Determinism: the loop runs on an injected `clock` and an injectable
`gauges_fn`, so unit tests (`tests/test_autoscaler.py`) and the chaos
harness drive threshold crossings on a fake clock with scripted gauges.
At most one scaling decision per group per `dwell_s` (retires of
already-draining replicas are completion of a prior decision and are
exempt). Every decision is journaled on the group (epoch-stamped,
span-recorded, replicated to the standby via
`FailoverManager.wal_scale`) so failover replays scaling state exactly
and a deposed master's decisions are refused by the PR-5 fence.
"""
from __future__ import annotations

import time
from dataclasses import asdict, dataclass, fields
from typing import Any, Callable, Dict, List, Optional


@dataclass(frozen=True)
class AutoscalePolicy:
    """Per-group scaling policy; defaults come from ClusterConfig.

    Wire form (``to_wire``/``from_wire``) is a plain dict so it rides
    the group's journal entry through failover snapshots unchanged.
    """

    # scale-OUT: interactive p95 queue wait above this = SLO breach
    deadline_slack_s: float = 1.0
    # scale-IN: p95 below scale_in_frac * deadline_slack_s = underload
    scale_in_frac: float = 0.25
    # retire a draining replica only after this window with zero
    # undelivered journal entries (zero admitted-request loss)
    drain_window_s: float = 10.0
    min_replicas: int = 1
    max_replicas: int = 4
    # min seconds between scaling DECISIONS for the group (damper)
    dwell_s: float = 15.0
    # role split: prompts >= this many tokens are PREFILL-heavy and
    # route to the prefill-tuned replica (0 disables the split)
    prefill_len_threshold: int = 0
    # prefill replicas are spawned with this chunked-prefill setting
    prefill_chunk: int = 0
    # spawn a prefill (not decode) replica when at least this fraction
    # of routed admissions since the last decision were prefill-heavy
    prefill_share: float = 0.25
    # rebalance when max-min WFQ debt across decode replicas exceeds it
    rebalance_debt: float = 2.0
    # predictive scale-AHEAD (ISSUE 18; Clockwork-style provisioning,
    # Gujarati et al., OSDI 2020): forecast horizon in seconds (0
    # disables). A Holt (level+trend) forecast of the group's admission
    # arrival rate — fed from the gateway's cumulative per-class
    # ``admitted`` counters via group_gauges — spawns BEFORE the p95
    # breach when the predicted rate at the horizon exceeds serving
    # capacity (active replicas x predict_capacity_rps), and suppresses
    # scale-in while a breach is forecast (never below reactive).
    predict_horizon_s: float = 0.0
    predict_alpha: float = 0.5          # level smoothing (EWMA weight)
    predict_beta: float = 0.3           # trend smoothing
    predict_capacity_rps: float = 1.0   # per-replica sustainable req/s
    enabled: bool = True

    def __post_init__(self) -> None:
        if self.deadline_slack_s <= 0:
            raise ValueError("autoscale: deadline_slack_s must be > 0")
        if not 0.0 <= self.scale_in_frac < 1.0:
            raise ValueError("autoscale: scale_in_frac must be in [0, 1)")
        if self.drain_window_s < 0 or self.dwell_s < 0:
            raise ValueError("autoscale: windows must be >= 0")
        if self.min_replicas < 1:
            raise ValueError("autoscale: min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("autoscale: max_replicas < min_replicas")
        if self.prefill_len_threshold < 0 or self.prefill_chunk < 0:
            raise ValueError("autoscale: prefill knobs must be >= 0")
        if not 0.0 <= self.prefill_share <= 1.0:
            raise ValueError("autoscale: prefill_share must be in [0, 1]")
        if self.rebalance_debt <= 0:
            raise ValueError("autoscale: rebalance_debt must be > 0")
        if self.predict_horizon_s < 0:
            raise ValueError("autoscale: predict_horizon_s must be >= 0")
        if not 0.0 < self.predict_alpha <= 1.0 \
                or not 0.0 < self.predict_beta <= 1.0:
            raise ValueError("autoscale: predict smoothing factors must "
                             "be in (0, 1]")
        if self.predict_capacity_rps <= 0:
            raise ValueError("autoscale: predict_capacity_rps must "
                             "be > 0")

    @classmethod
    def keys(cls) -> frozenset:
        return frozenset(f.name for f in fields(cls))

    @classmethod
    def from_config(cls, config: Any,
                    overrides: Optional[Dict[str, Any]] = None
                    ) -> "AutoscalePolicy":
        """ClusterConfig defaults, then the lm_serve spec's overrides."""
        base = {
            "deadline_slack_s": float(config.autoscale_deadline_slack_s),
            "drain_window_s": float(config.autoscale_drain_window_s),
            "min_replicas": int(config.autoscale_min_replicas),
            "max_replicas": int(config.autoscale_max_replicas),
            "dwell_s": float(config.autoscale_dwell_s),
        }
        if overrides:
            unknown = set(overrides) - cls.keys()
            if unknown:
                raise ValueError(
                    f"autoscale: unknown policy keys {sorted(unknown)}; "
                    f"valid: {sorted(cls.keys())}")
            base.update(overrides)
        return cls(**base)

    def merged(self, updates: Dict[str, Any]) -> "AutoscalePolicy":
        """New validated policy with ``updates`` applied (lm_autoscale)."""
        unknown = set(updates) - self.keys()
        if unknown:
            raise ValueError(
                f"autoscale: unknown policy keys {sorted(unknown)}; "
                f"valid: {sorted(self.keys())}")
        return AutoscalePolicy(**{**asdict(self), **updates})

    def to_wire(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_wire(cls, d: Dict[str, Any]) -> "AutoscalePolicy":
        return cls(**{k: v for k, v in d.items() if k in cls.keys()})


class Autoscaler:
    """The control loop. One instance per LMPoolManager; ``tick()`` is
    called from the manager's ``pump_once`` (so it only ever runs at the
    acting master — the same gate every managed mutation sits behind).

    ``gauges_fn(group) -> {replica: {"interactive_p95", "n",
    "backlog"}}`` is injectable for deterministic tests; the default
    reads the live ``lm_qos`` gauges through the manager.
    """

    def __init__(self, manager: Any,
                 clock: Callable[[], float] = time.time) -> None:
        self.manager = manager
        self.clock = clock
        self.gauges_fn: Optional[Callable[[str], Dict[str, Any]]] = None
        # Holt forecast memory per group (ISSUE 18): soft derived state —
        # the DECISIONS it produces journal/replicate like any other;
        # after failover the forecast reseeds from live counters in one
        # sample interval. {group: {t, admitted, level, trend,
        # predicted, spawns}}
        self._forecast: Dict[str, Dict[str, Any]] = {}

    # -- signal helpers ---------------------------------------------------

    @staticmethod
    def _p95(gauges: Dict[str, Any]) -> float:
        """Worst interactive p95 across replicas that have samples."""
        vals = [float(g.get("interactive_p95", 0.0))
                for g in gauges.values() if int(g.get("n", 0)) > 0]
        return max(vals) if vals else 0.0

    @staticmethod
    def _backlog(gauges: Dict[str, Any]) -> int:
        return sum(int(g.get("backlog", 0)) for g in gauges.values())

    # -- predictive scale-ahead (ISSUE 18) --------------------------------

    def forecast_view(self, name: str) -> Dict[str, Any]:
        """Forecast gauges for ``lm_qos`` group status / shell display."""
        st = self._forecast.get(name)
        if st is None:
            return {"predicted_rate": 0.0, "predictive_spawns": 0}
        return {"predicted_rate": round(float(st["predicted"]), 4),
                "predictive_spawns": int(st["spawns"])}

    def _forecast_update(self, name: str, policy: AutoscalePolicy,
                         gauges: Dict[str, Any], now: float) -> float:
        """Advance the group's Holt (level+trend) arrival-rate forecast
        one sample and return the predicted rate at the horizon.

        The signal is the sum of the gateway's cumulative per-class
        ``admitted`` counters across replicas (group_gauges): the
        discrete rate between ticks feeds ``level' = a*inst +
        (1-a)*level``, ``trend' = b*(level'-level)/dt + (1-b)*trend``,
        and the horizon estimate is ``level' + trend'*horizon`` —
        trend-following, so a ramp crosses the capacity threshold
        BEFORE the queue-wait p95 breaches. Deterministic: runs on the
        injected clock, and a counter regression (group rebuilt,
        replica set changed under failover) reseeds instead of
        producing a negative rate."""
        if policy.predict_horizon_s <= 0:
            self._forecast.pop(name, None)
            return 0.0
        admitted = 0
        for g in gauges.values():
            adm = g.get("admitted") or {}
            admitted += sum(int(v) for v in adm.values())
        st = self._forecast.get(name)
        if st is None or admitted < st["admitted"]:
            self._forecast[name] = {"t": now, "admitted": admitted,
                                    "level": None, "trend": 0.0,
                                    "predicted": 0.0, "spawns": 0}
            return 0.0
        dt = now - st["t"]
        if dt <= 0:
            return float(st["predicted"])
        inst = (admitted - st["admitted"]) / dt
        if st["level"] is None:
            # Holt initialization: the first rate sample seeds the level
            # outright with zero trend — one sample carries no slope, and
            # deriving one against the zero seed made any first arrival
            # after a (re)seed look like a steep ramp, spawning on noise
            level, trend = inst, 0.0
        else:
            a, b = policy.predict_alpha, policy.predict_beta
            level = a * inst + (1 - a) * st["level"]
            trend = (b * ((level - st["level"]) / dt)
                     + (1 - b) * st["trend"])
        predicted = max(0.0, level + trend * policy.predict_horizon_s)
        st.update(t=now, admitted=admitted, level=level, trend=trend,
                  predicted=predicted)
        return predicted

    # -- the loop ---------------------------------------------------------

    def tick(self) -> List[Dict[str, Any]]:
        """One control-loop pass over every group; returns the decisions
        taken this tick (journaled on the group by the manager)."""
        decisions: List[Dict[str, Any]] = []
        for name in self.manager.group_names():
            try:
                decisions.extend(self._tick_group(name))
            except Exception:  # noqa: BLE001 - the loop must survive a
                # single group's bad tick; the next pump retries it
                import logging
                logging.getLogger("idunno.autoscaler").exception(
                    "autoscale tick failed for group %r", name)
        return decisions

    def _tick_group(self, name: str) -> List[Dict[str, Any]]:
        view = self.manager.group_view(name)
        if view is None:
            return []
        policy: AutoscalePolicy = view["policy"]
        if not policy.enabled:
            return []
        now = self.clock()
        out: List[Dict[str, Any]] = []

        # 1. complete in-flight retires: a DRAINING replica with zero
        #    undelivered journal entries, past the drain window, goes.
        #    This finishes a prior decision, so it is dwell-exempt.
        #    Every decision this tick carries the forecast's view
        #    (predicted_rate) so the journal is auditable per decision,
        #    not just per predictive spawn (ISSUE 20 satellite) —
        #    retires run before this tick's forecast sample, so they
        #    stamp the LAST prediction.
        fc_prev = self.forecast_view(name)["predicted_rate"]
        for rname, meta in sorted(view["replicas"].items()):
            if meta["state"] != "draining":
                continue
            if (meta["undelivered"] == 0
                    and now - meta["t_drain"] >= policy.drain_window_s):
                d = self.manager.group_retire(name, rname,
                                              predicted_rate=fc_prev)
                if d:
                    out.append(d)

        view = self.manager.group_view(name)
        if view is None:
            return out

        # 1b. quarantine-and-drain (ISSUE 20): an ACTIVE replica on a
        #     node the differential-health plane QUARANTINED stops
        #     taking new routing now — spawn its replacement first
        #     (capacity), then mark it draining. Dwell-exempt like
        #     retire completion: a gray failure does not wait out the
        #     damper. The drain → retire path is the ordinary one, so
        #     zero admitted requests are lost; if the victim is the
        #     LAST active replica and no replacement could place,
        #     retire_start refuses and it keeps serving (availability
        #     beats health).
        quarantined = set(self.manager._quarantined_hosts())
        if quarantined:
            victims = sorted(
                r for r, m in view["replicas"].items()
                if m["state"] == "active"
                and m.get("node") in quarantined)
            n_active = sum(1 for m in view["replicas"].values()
                           if m["state"] == "active")
            for rname in victims:
                if n_active < policy.max_replicas:
                    d = self.manager.group_spawn(
                        name, role=view["replicas"][rname]["role"],
                        quarantine=True, replaced=rname,
                        predicted_rate=fc_prev)
                    if d:
                        out.append(d)
                        n_active += 1
                d = self.manager.group_retire_start(
                    name, replica=rname, quarantine=True,
                    predicted_rate=fc_prev)
                if d:
                    out.append(d)
                    n_active -= 1
            if victims:
                view = self.manager.group_view(name)
                if view is None:
                    return out

        # quarantined-but-undrainable replicas don't count as capacity:
        # thresholds below see only healthy actives
        active = sorted(r for r, m in view["replicas"].items()
                        if m["state"] == "active"
                        and m.get("node") not in quarantined)
        if not active:
            return out
        if now - view["t_last_decision"] < policy.dwell_s:
            return out

        gauges = (self.gauges_fn or self.manager.group_gauges)(name)
        gauges = {r: g for r, g in gauges.items() if r in active}
        p95 = self._p95(gauges)
        backlog = self._backlog(gauges)
        pred = self._forecast_update(name, policy, gauges, now)

        # 2. scale OUT on SLO breach
        if p95 > policy.deadline_slack_s and len(active) < policy.max_replicas:
            role = "decode"
            rc = view["route_counts"]
            if (policy.prefill_len_threshold > 0
                    and not any(view["replicas"][r]["role"] == "prefill"
                                for r in active)
                    and rc["total"] > 0
                    and rc["prefill"] / rc["total"] >= policy.prefill_share):
                role = "prefill"
            d = self.manager.group_spawn(name, role=role, p95=round(p95, 4),
                                         predicted_rate=round(pred, 4))
            if d:
                out.append(d)
            return out

        # 2b. predictive scale-AHEAD (ISSUE 18): the forecast arrival
        #     rate at the horizon exceeds what the active replicas can
        #     sustain — spawn BEFORE the reactive breach. Journaled
        #     exactly like a reactive spawn, tagged predictive.
        if (pred > len(active) * policy.predict_capacity_rps
                and len(active) < policy.max_replicas):
            d = self.manager.group_spawn(
                name, role="decode", predictive=True,
                predicted_rate=round(pred, 4), p95=round(p95, 4))
            if d:
                self._forecast[name]["spawns"] += 1
                metrics = getattr(getattr(self.manager, "service", None),
                                  "metrics", None)
                if metrics is not None:
                    metrics.record_counter("predictive_spawns")
                out.append(d)
            return out

        # 3. scale IN at underload: idle group, or p95 well under slack.
        #    (The gateway's wait window is cumulative, so "no backlog"
        #    is the reliable idle signal once traffic stops.) Suppressed
        #    while the forecast predicts the SMALLER replica set would
        #    breach — predictive never drops below what reactive keeps.
        if pred > (len(active) - 1) * policy.predict_capacity_rps \
                and policy.predict_horizon_s > 0:
            return out
        low = (backlog == 0
               or p95 < policy.scale_in_frac * policy.deadline_slack_s)
        if low and len(active) > policy.min_replicas:
            d = self.manager.group_retire_start(
                name, p95=round(p95, 4), predicted_rate=round(pred, 4))
            if d:
                out.append(d)
            return out

        # 4. rebalance tenants by WFQ debt across decode replicas
        debts = view["debts"]
        if len(debts) >= 2:
            hi = max(debts.values())
            lo = min(debts.values())
            if hi - lo > policy.rebalance_debt:
                d = self.manager.group_rebalance(
                    name, predicted_rate=round(pred, 4))
                if d:
                    out.append(d)
        return out
