"""Cross-request radix prefix cache (SGLang RadixAttention-style,
PAPERS.md) over a `KVBlockPool` (`engine/kv_blocks.py`).

A radix tree keyed by block_size-token chunks of the PER-REQUEST prompt
(the pool-level static ``prefix=`` is shared by construction and sits in
front of every chain at fixed absolute positions). Each node owns one
block of the pool — the KV for its chunk's token positions — so a
root-to-node path is a ready-to-splice block chain for that token
prefix. Admission (`DecodeServer._admit`) looks up the longest cached
chain, gathers it, and prefills only the remaining suffix; after the
prefill it inserts the request's own full blocks so the NEXT request
sharing the prompt head hits them.

Lifecycle:
  - lookup/insert stamp every touched node with a monotonic LRU clock.
  - A request acquires (increfs) its whole chain at admission and
    releases it at retirement/cancel — pinned chains can never be
    evicted mid-flight.
  - Allocation under pool pressure evicts the LRU refcount-0 LEAF,
    repeatedly; a held node is never a candidate, and an inner node is
    only freed after its subtree (children pin their chain prefix by
    structure, not by refcount).
  - When eviction cannot free a block (every block pinned by live
    requests), insertion is skipped — serving NEVER blocks or fails on
    cache pressure; the request just doesn't seed the tree
    (``insert_skips`` counts these).

The reference recomputes every query from scratch
(`mp4_machinelearning.py:541-616`); there is no counterpart subsystem.
"""
from __future__ import annotations

from typing import Any

from idunno_tpu.engine.kv_blocks import KVBlockPool


class _Node:
    __slots__ = ("chunk", "block", "children", "parent", "stamp")

    def __init__(self, chunk: tuple[int, ...], block: int,
                 parent: "_Node | None", stamp: int) -> None:
        self.chunk = chunk
        self.block = block
        self.children: dict[tuple[int, ...], _Node] = {}
        self.parent = parent
        self.stamp = stamp


class RadixPrefixCache:
    def __init__(self, pool: KVBlockPool) -> None:
        self.pool = pool
        self.block_size = pool.block_size
        self._root = _Node((), -1, None, 0)
        self._clock = 0
        self.evictions = 0
        self.insert_skips = 0
        self.inserted_blocks = 0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _chunks(self, tokens: list[int]):
        bs = self.block_size
        for j in range(len(tokens) // bs):
            yield tuple(tokens[j * bs:(j + 1) * bs])

    # -- query ------------------------------------------------------------

    def lookup(self, tokens: list[int]) -> list[_Node]:
        """Longest cached chain for ``tokens`` (block-aligned: only full
        block_size chunks can match). Touches the chain's LRU stamps."""
        stamp = self._tick()
        node, chain = self._root, []
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            child.stamp = stamp
            chain.append(child)
            node = child
        return chain

    def acquire(self, chain: list[_Node]) -> None:
        for nd in chain:
            self.pool.incref(nd.block)

    def release(self, chain: list[_Node]) -> None:
        for nd in chain:
            self.pool.decref(nd.block)

    # -- growth -----------------------------------------------------------

    def insert(self, tokens: list[int], row_cache: Any,
               pos_offset: int) -> list[_Node]:
        """Ensure a chain exists for every FULL block of ``tokens``,
        writing newly created nodes' KV from ``row_cache`` (token i of
        ``tokens`` lives at cache position ``pos_offset + i`` — the
        pool-level static prefix length at the serving tier). Existing
        nodes are reused untouched: the causal model makes their stored
        KV bit-identical to what this request's prefill just computed at
        the same positions. Best-effort — returns the chain built so
        far (possibly short) when the pool is exhausted even after
        eviction.

        The returned chain comes back ACQUIRED (each node increffed as
        the walk pins it — so the insert's own eviction loop can never
        free a node of the chain being built); the caller owns exactly
        one reference per node and must `release` it at retirement."""
        stamp = self._tick()
        node, chain = self._root, []
        for j, chunk in enumerate(self._chunks(tokens)):
            child = node.children.get(chunk)
            if child is None:
                bid = self._alloc_block()
                if bid is None:
                    self.insert_skips += 1
                    break
                self.pool.write_block(bid, row_cache,
                                      pos_offset + j * self.block_size)
                child = _Node(chunk, bid, node, stamp)
                node.children[chunk] = child
                self.inserted_blocks += 1
            child.stamp = stamp
            self.pool.incref(child.block)
            chain.append(child)
            node = child
        return chain

    def graft(self, tokens: list[int],
              fetched: list[tuple[list[int], Any]],
              start_depth: int) -> int:
        """Splice cluster-fetched raw blocks (`serve/cluster_prefix.py`)
        into the tree: ``fetched`` holds (chunk, leaf arrays) pairs for
        consecutive depths starting at ``start_depth`` of ``tokens``.
        Chunks already present are REUSED, not reallocated — grafting is
        naturally idempotent, a duplicated fetch converges on the same
        tree (the `prefix_fetch` contract anchor). Best-effort like
        `insert`: stops when the pool is exhausted even after eviction.
        Returns the number of NEW blocks written; nothing is acquired —
        the caller re-runs `lookup` to pin the extended chain."""
        stamp = self._tick()
        node = self._root
        chunks = list(self._chunks(tokens))
        # pin the whole walked path (like `insert`): the alloc loop's
        # eviction must never free a node of the chain being extended
        pinned: list[_Node] = []
        try:
            for j in range(start_depth):
                node = node.children.get(chunks[j])
                if node is None:
                    raise ValueError(
                        f"graft start_depth {start_depth} deeper than "
                        f"the local chain (missing chunk {j})")
                self.pool.incref(node.block)
                pinned.append(node)
            wrote = 0
            for i, (chunk, arrays) in enumerate(fetched):
                chunk = tuple(int(t) for t in chunk)
                if chunk != chunks[start_depth + i]:
                    raise ValueError("graft chunk does not match the "
                                     "prompt prefix at its depth")
                child = node.children.get(chunk)
                if child is None:
                    bid = self._alloc_block()
                    if bid is None:
                        self.insert_skips += 1
                        break
                    self.pool.write_raw_block(bid, arrays)
                    child = _Node(chunk, bid, node, stamp)
                    node.children[chunk] = child
                    self.inserted_blocks += 1
                    wrote += 1
                child.stamp = stamp
                self.pool.incref(child.block)
                pinned.append(child)
                node = child
            return wrote
        finally:
            for nd in pinned:
                self.pool.decref(nd.block)

    def _alloc_block(self) -> int | None:
        while True:
            bid = self.pool.alloc()
            if bid is not None:
                return bid
            if not self._evict_one():
                return None

    def _evict_one(self) -> bool:
        """Free the least-recently-used refcount-0 LEAF node's block.
        False when no node is evictable (every leaf pinned)."""
        best = None
        stack = list(self._root.children.values())
        while stack:
            nd = stack.pop()
            if nd.children:
                stack.extend(nd.children.values())
                continue
            if self.pool.refcount(nd.block) == 0 and (
                    best is None or nd.stamp < best.stamp):
                best = nd
        if best is None:
            return False
        del best.parent.children[best.chunk]
        self.pool.free(best.block)
        self.evictions += 1
        return True

    # -- introspection ----------------------------------------------------

    def num_nodes(self) -> int:
        n, stack = 0, list(self._root.children.values())
        while stack:
            nd = stack.pop()
            n += 1
            stack.extend(nd.children.values())
        return n
