"""QoS admission gateway for LM serving pools (ISSUE 4 tentpole).

Front door between `lm_submit` and a pool's decode loop:

- **Per-tenant token buckets** rate-limit admission (``rate`` requests/s
  refill, ``burst`` capacity; rate 0 = the burst is the whole budget,
  rate None = unlimited).
- **Weighted fair queueing** orders non-deadlined requests within a
  class by start-time-fair virtual finish tags (cost 1/weight per
  request), so a heavy tenant cannot starve a light one.
- **EDF within a class**: any request with a ``deadline_ms`` sorts by
  absolute deadline ahead of all undeadlined ones; ``interactive``
  always dispatches before ``batch``.
- **Backpressure** (`serve/admission.py:BackpressureConfig`) sheds at
  admission time from live pool gauges — before the decode loop
  saturates, not after.
- **Expiry**: a queued request whose deadline passes is never
  dispatched; `take` returns it separately so the serving loop can
  complete it with ``rejected="expired"``.

All decisions go through an injectable monotonic ``clock`` so the unit
tests (`tests/test_gateway.py`) drive quotas/EDF/expiry deterministically
with a fake clock — no wall-clock sleeps in the fast lane.

The gateway is pool-local (one instance per `LMServingLoop`); the
manager journal records sheds/expiries as terminal states so recovery
never resubmits a request the gateway already rejected
(`serve/lm_manager.py`).
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from idunno_tpu.serve.admission import (
    PRIORITIES, SHED_REASONS, AdmissionShed, BackpressureConfig)
from idunno_tpu.serve.metrics import _percentile

DEFAULT_TENANT = "default"
_WAIT_WINDOW = 512       # queue-wait samples kept per class for p50/p99
_SHED_RING = 20          # recent sheds surfaced in lm-tail

_SPEC_KEYS = frozenset({
    "tenants", "default", "max_queue",
    "batch_wait_slack", "interactive_wait_slack", "min_free_kv_frac"})
_QUOTA_KEYS = frozenset({"rate", "burst", "weight"})


class TokenBucket:
    """Classic token bucket with an externally supplied ``now``."""

    __slots__ = ("rate", "burst", "_tokens", "_t")

    def __init__(self, rate: float | None, burst: float, now: float) -> None:
        self.rate = None if rate is None else float(rate)
        self.burst = float(burst)
        self._tokens = self.burst
        self._t = now

    def try_take(self, now: float) -> bool:
        if self.rate is None:
            return True
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


def _norm_quota(q: dict | None) -> dict:
    q = dict(q or {})
    unknown = set(q) - _QUOTA_KEYS
    if unknown:
        raise ValueError(f"unknown quota keys: {sorted(unknown)}")
    rate = q.get("rate")
    out = {"rate": None if rate is None else float(rate),
           "burst": float(q.get("burst", 1.0)),
           "weight": float(q.get("weight", 1.0))}
    if out["rate"] is not None and out["rate"] < 0:
        raise ValueError("quota rate must be >= 0 (None = unlimited)")
    if out["burst"] < 1.0:
        raise ValueError("quota burst must be >= 1")
    if out["weight"] <= 0:
        raise ValueError("quota weight must be > 0")
    return out


@dataclass
class _Entry:
    rid: int
    tenant: str
    priority: str
    payload: Any
    t_enq: float
    deadline: float | None   # absolute clock time, None = no deadline
    ft: float                # WFQ virtual finish tag
    seq: int

    def key(self) -> tuple:
        return (self.deadline if self.deadline is not None else math.inf,
                self.ft, self.seq)


@dataclass
class _ClassState:
    queue: list = field(default_factory=list)
    vt: float = 0.0                       # class virtual time
    last_ft: dict = field(default_factory=dict)   # tenant → last finish tag
    admitted: int = 0
    dispatched: int = 0
    expired: int = 0
    shed: dict = field(default_factory=lambda: {r: 0 for r in SHED_REASONS
                                                if r != "expired"})
    waits: deque = field(default_factory=lambda: deque(maxlen=_WAIT_WINDOW))


class AdmissionGateway:
    """One gateway fronting one serving loop; all methods thread-safe."""

    def __init__(self, spec: dict | None = None, *,
                 clock: Callable[[], float] = time.monotonic) -> None:
        spec = self.validate_spec(spec)
        self.clock = clock
        self._lock = threading.Lock()
        self._quotas = {t: _norm_quota(q)
                        for t, q in (spec.get("tenants") or {}).items()}
        self._default_quota = _norm_quota(spec.get("default"))
        self.max_queue = int(spec.get("max_queue", 256))
        self.backpressure = BackpressureConfig(
            batch_wait_slack=float(spec.get("batch_wait_slack", 2.0)),
            interactive_wait_slack=float(
                spec.get("interactive_wait_slack", 4.0)),
            min_free_kv_frac=float(spec.get("min_free_kv_frac", 0.125)))
        self._buckets: dict[str, TokenBucket] = {}
        self._classes = {p: _ClassState() for p in PRIORITIES}
        self._tenants: dict[str, dict] = {}   # per-tenant counters
        self._seq = 0
        self._recent_sheds: deque = deque(maxlen=_SHED_RING)

    @staticmethod
    def validate_spec(spec: dict | bool | None) -> dict:
        """Normalize/validate a gateway spec (loudly, before any registry
        mutation in `serve/control.py`). ``True``/None/{} = all defaults."""
        if spec is None or spec is True:
            spec = {}
        if not isinstance(spec, dict):
            raise ValueError(f"gateway spec must be a dict, got "
                             f"{type(spec).__name__}")
        unknown = set(spec) - _SPEC_KEYS
        if unknown:
            raise ValueError(f"unknown gateway spec keys: {sorted(unknown)}")
        for t, q in (spec.get("tenants") or {}).items():
            _norm_quota(q)
        _norm_quota(spec.get("default"))
        if int(spec.get("max_queue", 256)) < 1:
            raise ValueError("gateway max_queue must be >= 1")
        return dict(spec)

    # -- internals (call with self._lock held) ----------------------------

    def _quota(self, tenant: str) -> dict:
        return self._quotas.get(tenant, self._default_quota)

    def _tenant_counters(self, tenant: str) -> dict:
        return self._tenants.setdefault(
            tenant, {"admitted": 0, "dispatched": 0, "shed": 0, "expired": 0})

    def _queued_total_locked(self) -> int:
        return sum(len(c.queue) for c in self._classes.values())

    def _shed_locked(self, tenant: str, priority: str, reason: str,
                     detail: str) -> AdmissionShed:
        self._classes[priority].shed[reason] += 1
        self._tenant_counters(tenant)["shed"] += 1
        self._recent_sheds.append({"tenant": tenant, "priority": priority,
                                   "reason": reason, "detail": detail})
        return AdmissionShed(reason, detail)

    # -- submit side ------------------------------------------------------

    def admit(self, rid: int, payload: Any, *, tenant: str = DEFAULT_TENANT,
              priority: str = "interactive", deadline_ms: float | None = None,
              pool_gauges: dict | None = None, readmit: bool = False) -> None:
        """Admit-or-shed + enqueue, atomically. Raises AdmissionShed on
        rejection (counters already recorded). ``readmit=True`` bypasses
        quota/backpressure/queue-full: the manager re-forwards
        already-admitted requests after node death, and a replay must
        never be shed (the client was told it was in)."""
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, "
                             f"got {priority!r}")
        if deadline_ms is not None and float(deadline_ms) <= 0:
            raise ValueError("deadline_ms must be > 0")
        now = self.clock()
        with self._lock:
            cls = self._classes[priority]
            if not readmit:
                if not self._bucket_locked(tenant, now).try_take(now):
                    raise self._shed_locked(
                        tenant, priority, "quota",
                        f"tenant {tenant!r} over rate limit")
                if self._queued_total_locked() >= self.max_queue:
                    raise self._shed_locked(
                        tenant, priority, "queue_full",
                        f"gateway queue at max_queue={self.max_queue}")
                gauges = dict(pool_gauges or {})
                gauges["waiting"] = (int(gauges.get("waiting", 0))
                                    + self._queued_total_locked())
                detail = self.backpressure.pressure_reason(priority, gauges)
                if detail is not None:
                    raise self._shed_locked(tenant, priority,
                                            "backpressure", detail)
            quota = self._quota(tenant)
            start = max(cls.vt, cls.last_ft.get(tenant, 0.0))
            ft = start + 1.0 / quota["weight"]
            cls.last_ft[tenant] = ft
            self._seq += 1
            cls.queue.append(_Entry(
                rid=rid, tenant=tenant, priority=priority, payload=payload,
                t_enq=now,
                deadline=(None if deadline_ms is None
                          else now + float(deadline_ms) / 1000.0),
                ft=ft, seq=self._seq))
            cls.admitted += 1
            self._tenant_counters(tenant)["admitted"] += 1

    def _bucket_locked(self, tenant: str, now: float) -> TokenBucket:
        b = self._buckets.get(tenant)
        if b is None:
            q = self._quota(tenant)
            b = self._buckets[tenant] = TokenBucket(q["rate"], q["burst"], now)
        return b

    # -- dispatch side (serving loop thread) ------------------------------

    def take(self, budget: int,
             now: float | None = None) -> tuple[list[_Entry], list[_Entry]]:
        """Pop up to ``budget`` dispatchable entries (class order, then
        EDF, then WFQ finish tags) plus ALL expired entries (returned
        regardless of budget — an expired request must complete as
        rejected promptly, not wait for dispatch headroom)."""
        if now is None:
            now = self.clock()
        ready: list[_Entry] = []
        expired: list[_Entry] = []
        with self._lock:
            for p in PRIORITIES:
                cls = self._classes[p]
                if not cls.queue:
                    continue
                cls.queue.sort(key=_Entry.key)
                keep: list[_Entry] = []
                for e in cls.queue:
                    if e.deadline is not None and e.deadline < now:
                        expired.append(e)
                        cls.expired += 1
                        self._tenant_counters(e.tenant)["expired"] += 1
                    elif len(ready) < budget:
                        ready.append(e)
                        cls.dispatched += 1
                        cls.vt = max(cls.vt, e.ft)
                        cls.waits.append(max(0.0, now - e.t_enq))
                        self._tenant_counters(e.tenant)["dispatched"] += 1
                    else:
                        keep.append(e)
                cls.queue = keep
        return ready, expired

    def cancel(self, rid: int) -> _Entry | None:
        """Remove a still-queued entry (None = not queued here)."""
        with self._lock:
            for cls in self._classes.values():
                for i, e in enumerate(cls.queue):
                    if e.rid == rid:
                        del cls.queue[i]
                        return e
        return None

    def queued(self) -> int:
        with self._lock:
            return self._queued_total_locked()

    def drain(self) -> list[_Entry]:
        """Pop everything (pool stop: pending entries error upstream)."""
        with self._lock:
            out = [e for p in PRIORITIES for e in self._classes[p].queue]
            for cls in self._classes.values():
                cls.queue = []
            return out

    # -- observability ----------------------------------------------------

    def recent_sheds(self) -> list[dict]:
        with self._lock:
            return [dict(s) for s in self._recent_sheds]

    def stats(self) -> dict:
        """Per-class and per-tenant counters + queue-wait percentiles +
        reject rates — the `lm_stats`/`lm_qos`/`serve/metrics.py` surface."""
        with self._lock:
            classes = {}
            for p, cls in self._classes.items():
                shed_n = sum(cls.shed.values())
                submitted = cls.admitted + shed_n
                waits = sorted(cls.waits)
                classes[p] = {
                    "queued": len(cls.queue),
                    "admitted": cls.admitted,
                    "dispatched": cls.dispatched,
                    "expired": cls.expired,
                    "shed": dict(cls.shed),
                    "reject_rate": ((shed_n + cls.expired) / submitted
                                    if submitted else 0.0),
                    # p95 is the autoscaler's Clockwork-style SLO signal
                    # (serve/autoscaler.py): scale-out triggers when
                    # interactive p95 crosses the deadline slack
                    "queue_wait_s": {"p50": _percentile(waits, 50),
                                     "p95": _percentile(waits, 95),
                                     "p99": _percentile(waits, 99),
                                     "n": len(waits)},
                }
            tenants = {}
            for t, c in self._tenants.items():
                q = self._quota(t)
                tenants[t] = dict(
                    c, queued=sum(1 for cls in self._classes.values()
                                  for e in cls.queue if e.tenant == t),
                    rate=q["rate"], burst=q["burst"], weight=q["weight"])
            return {"queued": self._queued_total_locked(),
                    "max_queue": self.max_queue,
                    "classes": classes, "tenants": tenants,
                    "recent_sheds": [dict(s) for s in self._recent_sheds]}
