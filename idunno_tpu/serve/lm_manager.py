"""Cluster management for the LM serving tier (round-2 VERDICT item 3).

Round 2's LM tier was node-local: ``lm_serve`` built a pool on whichever
node took the RPC, so decode pools and train jobs sat outside the
cluster's core guarantees — not placed by the coordinator, not fair-shared,
not journaled to the standby, and dead with their node (queued + in-flight
requests lost; train jobs resumed only by manual re-``train_start``). The
reference applies its guarantees to *all* work: coordinator task placement
and failed-worker reassignment (`mp4_machinelearning.py:706-760`), standby
metadata replication (`:971-1011`).

This manager runs on the acting master and closes that gap for the LM tier:

- **Placement**: ``serve()``/``train()`` pick the least-loaded alive node
  (measured load: the scheduler book's in-flight CNN tasks per host, plus
  managed pools/jobs already placed there) and issue the node-local verb
  over the control RPC.
- **Journaling**: every submitted request's full descriptor (prompt,
  max_new, temperature, *pinned* seed) and its completion tokens live in a
  master-side journal. Sampling seeds are pinned at admission (default:
  the global request id), so a replayed request — greedy OR sampled — is
  token-exact.
- **Standby replication**: ``to_wire()``/``load_wire()`` ride the
  FailoverManager snapshot, so the standby adopts the pool registry and
  the journal along with the task book.
- **Recovery**: on a pool node's death the manager re-issues ``lm_serve``
  on a survivor and resubmits every unfinished request; a dead train-job
  node gets ``train_start(resume=True)`` on a survivor, resuming from the
  job's last store checkpoint. On coordinator failover the new master
  conservatively requeues every unfinished request (completions drained
  from a pool but not yet replicated are unrecoverable from the node;
  pinned seeds make the replay exact, and the journal dedupes).

Threading: verbs arrive on RPC handler threads, the pump runs on the
master loop, membership changes on the monitor thread — one RLock guards
the registry; all transport calls happen OUTSIDE the lock (a slow or dead
peer must never stall the registry).
"""
from __future__ import annotations

import re
import threading
import time
from typing import Any

from idunno_tpu.comm.message import Message
from idunno_tpu.comm.retry import call_with_retry
from idunno_tpu.comm.transport import Transport, TransportError
from idunno_tpu.config import ClusterConfig
from idunno_tpu.membership.epoch import (StaleEpoch, StaleScope, place_scope,
                                         pool_scope, reply_is_stale,
                                         reply_stale_scope, stamp_scoped)
from idunno_tpu.membership.service import MembershipService
from idunno_tpu.serve.admission import PRIORITIES, shed_reason
from idunno_tpu.serve.autoscaler import Autoscaler, AutoscalePolicy
from idunno_tpu.utils.spans import stamp_trace
from idunno_tpu.utils.types import MemberStatus, MessageType


def _default_slots() -> int:
    """The measured serving default (engine/serve_lm.DEFAULT_SLOTS),
    imported lazily — the manager must stay importable without paying the
    engine's jax import on nodes that never serve."""
    from idunno_tpu.engine.serve_lm import DEFAULT_SLOTS
    return DEFAULT_SLOTS

CONTROL = "control"

# request lifecycle: pending (not yet on any node) -> inflight (forwarded,
# node id known) -> done (tokens journaled). Recovery moves inflight back
# to pending; done, failed (node rejected the request — permanent, e.g.
# a validation error), cancelled (client lm_cancel), shed (the pool's QoS
# gateway rejected admission — serve/gateway.py) and expired (deadline_ms
# passed while queued) are terminal — recovery/resubmission must never
# replay a request the client was already told is out.
_PENDING, _INFLIGHT, _DONE, _FAILED = "pending", "inflight", "done", "failed"
_CANCELLED = "cancelled"
_SHED, _EXPIRED = "shed", "expired"

# the pool poll's error-string shape ("request {rid} failed: ...") —
# parsed by the group poll to remap replica rids to group ids
_ERR_RE = re.compile(r"^request (\d+) failed: (.*)$", re.S)


class LMPoolManager:
    """Acting-master registry + journal + recovery for decode pools and
    train jobs. Constructed on every node (the standby needs one to adopt
    into); only the acting master's instance pumps or places."""

    # an inflight request older than this is assumed lost (node-side error
    # consumed by a failed poll, or a drained-but-undelivered reply) and is
    # requeued — exact replay, so the only cost is wasted decode. The
    # effective timeout scales with the request's max_new at the pool's
    # measured per-token rate (a legitimately long decode or a post-recovery
    # recompile must not be declared lost — ADVICE r3). Capped at
    # max_request_attempts total forwards, then FAILED loudly; pool-level
    # requeues (resize/recovery) reset the count — only per-request
    # suspicion consumes the budget.
    request_timeout_s = 120.0
    request_timeout_slack = 4.0      # x measured decode time, + timeout base
    max_request_attempts = 3
    # pool builds / in-place rebuilds and train starts compile XLA programs
    # node-side (~80 s for a first-time shape on TPU through the tunnel);
    # the default 30 s control-RPC timeout would declare every routine
    # resize dead mid-compile and leak the still-building loop
    build_rpc_timeout_s = 300.0

    def __init__(self, host: str, config: ClusterConfig,
                 transport: Transport, membership: MembershipService,
                 inference_service=None) -> None:
        self.host = host
        self.config = config
        self.transport = transport
        self.membership = membership
        self.service = inference_service      # scheduler book = load signal
        # minimum seconds between APPLIED slot resizes per pool (config-
        # driven; instance attribute so tests can pin it per-manager): a
        # rebuild is a full recompile + in-flight requeue, so a rate
        # hovering on a share boundary must not thrash the pool
        self.resize_dwell_s = float(config.lm_resize_dwell_s)
        # wall-clock source for request bookkeeping (t_submitted/
        # t_forwarded, fair-share windows, resize dwell, drain stamps) —
        # injectable so seeded harnesses can pin it; the autoscaler keeps
        # its own separately-injected clock
        self.wall = time.time
        # per-node span recorder (utils/spans.py), wired by serve/node.py;
        # None = tracing off. Journaled requests carry their trace ctx in
        # to_wire, so a trace survives failover adoption
        self.spans = None
        self._lock = threading.RLock()
        # name -> {"spec": dict, "node": str|None, "next_rid": int,
        #          "requests": {rid: descriptor}}
        self._pools: dict[str, dict[str, Any]] = {}
        # name -> {"spec": dict, "node": str|None, "status": dict|None}
        self._jobs: dict[str, dict[str, Any]] = {}
        # replica pool GROUPS (serve/autoscaler.py): an lm_serve spec
        # carrying autoscale={...} creates one of these instead of a
        # single pool. Replicas are ordinary entries in _pools named
        # "{group}@r{i}"; the group journals routing state + every
        # scaling decision so failover replays scaling exactly.
        # name -> {"spec", "policy", "replicas", "next_replica",
        #          "tenants", "next_grid", "rid_map", "idem",
        #          "decisions", "next_seq", "t_last_decision",
        #          "route_counts"}
        self._groups: dict[str, dict[str, Any]] = {}
        # per-pool WAL delta baseline: the last FULL wire entry the scope
        # standby ACKed, so _replicate_pool can ship journal deltas and
        # fall back to a full entry on any gap (ISSUE 15)
        self._wal_shipped: dict[str, dict[str, Any]] = {}
        # measured prefill ship-time EWMAs per prefill replica (ISSUE 20
        # satellite): manager-local soft state feeding prefill-role
        # routing; replica -> (ewma_s, n). Deliberately NOT in the group
        # wire form — an adopter starts cold and re-measures.
        self._ttft_ewma: dict[str, tuple[float, int]] = {}
        # cumulative journal rows compacted out of shipped WAL segments
        # below the delivered low-water mark (ISSUE 17 satellite;
        # metrics_export: pool_wal_truncated)
        self.wal_truncated = 0
        # the control loop; tick() runs from pump_once, so it inherits
        # the acting-master gate. clock/gauges_fn are injectable
        # (tests/test_autoscaler.py, chaos harness).
        self.autoscaler = Autoscaler(self)
        # FailoverManager backref (wired by serve/node.py) so scaling
        # decisions replicate to the standby between snapshots
        self.failover = None
        membership.on_change(self._on_member_change)

    # -- placement ---------------------------------------------------------

    def _load_score(self, host: str) -> float:
        """Measured load on ``host``: in-flight CNN tasks the scheduler
        book currently assigns to it, plus LM pools and train jobs this
        manager already placed there (each pool/job owns the device for
        its steps, so it weighs like an in-flight task stream)."""
        score = 0.0
        if self.service is not None:
            score += len(self.service.scheduler.book.in_flight(host))
        with self._lock:
            score += sum(1 for p in self._pools.values()
                         if p["node"] == host)
            score += sum(1 for j in self._jobs.values()
                         if j["node"] == host and not self._job_over(j))
        return score

    @staticmethod
    def _job_over(job: dict[str, Any]) -> bool:
        # stop_requested records the USER's intent even when the node was
        # unreachable at train_stop time — a stop-requested job must never
        # be auto-resumed by recovery
        if job.get("stop_requested"):
            return True
        st = job.get("status") or {}
        return bool(st.get("done") or st.get("stopped") or st.get("error"))

    def _place(self) -> str:
        alive = sorted(self.membership.members.alive_hosts())
        if not alive:
            raise ValueError("no alive hosts to place on")
        master = self.membership.acting_master()

        def key(h: str):
            # control-plane hosts carry the pump/replication loops: bias
            # ties away from the acting master (and, lighter, the standby)
            # without ever excluding them — a loaded worker still loses to
            # an idle master
            bias = (0.5 if h == master
                    else 0.25 if h == self.config.standby_coordinator
                    else 0.0)
            return (self._load_score(h) + bias, h)

        return min(alive, key=key)

    def _call(self, node: str, payload: dict[str, Any],
              timeout: float = 30.0,
              scope: str | None = None) -> dict[str, Any]:
        """Control RPC to a node's LOCAL lm tier (``local``=True keeps the
        receiving dispatcher from routing back into its own manager).
        Stamped with this manager's epoch view: a node that has seen a
        higher epoch fences us with StaleEpoch (a TransportError subclass,
        so every catch-site treats it as transient — requests stay
        pending/journal-safe — while the observe demotes this node and the
        pump stops on its next is_acting_master gate).

        ``scope`` (pool-directed mutating verbs) adds the per-pool fence
        stamp beside the cluster stamp: a node that has seen a higher
        epoch FOR THAT POOL rejects with a stale-scope reply — this
        manager then steps down for the named scope only (dropping the
        fenced pool/group registry entries) while every other pool keeps
        serving; the StaleScope raise reaches catch-sites as an ordinary
        transient, but the drop has already happened, so nothing
        retries into the fence."""
        payload = dict(payload, local=True,
                       epoch=list(self.membership.epoch.view()))
        if scope is not None:
            stamp_scoped(self.membership.scopes, scope, payload)
        reply = self.transport.call(
            node, CONTROL, Message(MessageType.INFERENCE, self.host,
                                   payload), timeout=timeout)
        if reply is None:
            raise TransportError(f"no reply from {node}")
        if reply_is_stale(self.membership.epoch, reply):
            e, owner = self.membership.epoch.view()
            raise StaleEpoch(f"{node} fenced this manager: epoch {e} "
                             f"owned by {owner}", e, owner)
        fenced = reply_stale_scope(self.membership.scopes, reply)
        if fenced is not None:
            # fence BEFORE raising: StaleScope subclasses TransportError,
            # and most catch-sites swallow those as transient — the drop
            # here is what guarantees no retry loop into the fence
            self._fence_scope(fenced)
            e, owner = self.membership.scopes.fence(fenced).view()
            raise StaleScope(f"{node} fenced scope {fenced}: epoch {e} "
                             f"owned by {owner}", fenced, e, owner)
        if reply.type is MessageType.ERROR:
            raise ValueError(f"{node}: {reply.payload.get('error')}")
        return reply.payload

    def _fence_scope(self, scope: str) -> None:
        """Step down for ONE fenced pool scope: drop its pools — and its
        group, whose _ensure_group_replicas would otherwise re-serve the
        replicas this manager no longer owns — from the local registry.
        The scope's new owner adopted an at-least-as-new journal (per-pool
        WAL), so keeping a fenced copy here would double-serve the pool.
        Everything else — other pools/groups, train jobs, the CNN book,
        cluster-wide mastership — is untouched: that isolation is the
        point of the per-pool fence."""
        with self._lock:
            dropped = [n for n in self._pools if pool_scope(n) == scope]
            for n in dropped:
                del self._pools[n]
            for n in [g for g in self._groups if pool_scope(g) == scope]:
                del self._groups[n]
                dropped.append(n)
        if dropped and self.service is not None:
            self.service.metrics.record_counter("pool_scope_fenced")

    # -- scope ownership (ISSUE 15) ----------------------------------------

    def step_down_scope(self, scope: str) -> None:
        """Public step-down for one scope: drop its pools/groups from the
        local registry (the new owner holds an at-least-as-new journal).
        Same semantics as a fence-driven step-down."""
        self._fence_scope(scope)

    def _scope_held_locally(self, scope: str) -> bool:
        with self._lock:
            return (any(pool_scope(n) == scope for n in self._pools)
                    or any(pool_scope(g) == scope for g in self._groups))

    def _scope_names_nonempty(self) -> bool:
        with self._lock:
            return bool(self._pools or self._groups)

    def _scope_owner(self, scope: str) -> str | None:
        """Where ``scope``'s journal should live: the gossiped claim if
        its holder is alive, else the deterministic rendezvous placement
        over the alive hosts. None when the membership plane carries no
        ownership map (bare test doubles) — callers then serve locally,
        the pre-ISSUE-15 behavior."""
        owners = getattr(self.membership, "owners", None)
        if owners is None:
            return None
        claimed = owners.owner(scope)
        alive = set(self.membership.members.alive_hosts())
        if claimed in alive:
            return claimed
        return place_scope(scope, self.config.hosts, alive,
                           quarantined=self._quarantined_hosts())

    def _quarantined_hosts(self) -> set[str]:
        """Hosts the differential-health plane has quarantined (gray
        failure: heartbeat-alive but limping). Routing-only input — the
        set is empty on bare test doubles without a ledger."""
        h = getattr(self.membership, "health", None)
        return h.quarantined() if h is not None else set()

    def _claim_scope(self, scope: str) -> None:
        """Advisory ownership claim, gossiped on membership payloads.
        Routing-only: the scope FENCE stays the safety mechanism — a
        stale claim costs one redirect hop, never correctness."""
        owners = getattr(self.membership, "owners", None)
        if owners is not None and owners.owner(scope) != self.host:
            owners.claim(scope, self.host)

    def _assign_scope(self, owner: str, spec: dict[str, Any],
                      scope: str) -> dict[str, Any] | None:
        """Hand an lm_serve spec to the scope's placed owner. The payload
        routes into the owner's ``_route_cluster`` (placement="assign",
        NOT local) so the owner's manager journals the pool. Returns the
        owner's reply, or None when the owner is unreachable — the caller
        then serves locally and claims the scope itself."""
        payload = dict(spec, verb="lm_serve", placement="assign",
                       epoch=list(self.membership.epoch.view()))
        stamp_scoped(self.membership.scopes, scope, payload)
        try:
            reply = self.transport.call(
                owner, CONTROL,
                Message(MessageType.INFERENCE, self.host, payload),
                timeout=self.build_rpc_timeout_s)
        except TransportError:
            return None
        if reply is None or reply_is_stale(self.membership.epoch, reply):
            return None
        if reply.type is MessageType.ERROR:
            raise ValueError(f"{owner}: {reply.payload.get('error')}")
        return dict(reply.payload, owner=owner)

    def _step_down_moved_scopes(self) -> None:
        """Drop any locally-held scope whose gossiped claim names another
        ALIVE host: its adopter minted a higher claim (and fence) — the
        fence would reject us anyway on the next stamped call, this just
        stops the pump from re-serving a moved scope in the window before
        that rejection lands."""
        owners = getattr(self.membership, "owners", None)
        if owners is None:
            return
        with self._lock:
            held = {pool_scope(n) for n in self._pools}
            held.update(pool_scope(g) for g in self._groups)
        alive = set(self.membership.members.alive_hosts())
        for scope in held:
            o = owners.owner(scope)
            if o is not None and o != self.host and o in alive:
                self.step_down_scope(scope)

    # -- pools: client surface (acting master) -----------------------------

    def serve(self, spec: dict[str, Any],
              assigned: bool = False) -> dict[str, Any]:
        """Place a decode pool on the least-loaded alive node and register
        it. ``spec`` is the node-local ``lm_serve`` payload (name,
        prompt_len, max_len, slots, draft, ...).

        Multi-owner placement (ISSUE 15): the pool's fence scope has a
        deterministic rendezvous owner over the alive hosts; when that
        owner is another host, this manager hands the WHOLE spec over
        (placement="assign") and the owner journals it locally — the
        acting master never funnels every scope. ``assigned=True`` is the
        landing half of that hop: serve here unconditionally, no
        re-forward."""
        spec = {k: v for k, v in spec.items()
                if k not in ("verb", "placement", "local", "reload")}
        scope = pool_scope(spec["name"])
        if not assigned and not self._scope_held_locally(scope):
            owner = self._scope_owner(scope)
            if owner is not None and owner != self.host:
                out = self._assign_scope(owner, spec, scope)
                if out is not None:
                    return out
                # owner unreachable: serve locally below and claim the
                # scope ourselves so routing follows the journal
        auto = spec.pop("autoscale", None)
        if auto is not None:
            return self._serve_group(spec, auto)
        name = spec["name"]
        with self._lock:
            if name in self._groups:
                raise ValueError(f"{name!r} is a replica group; serve "
                                 "replicas through its autoscale spec")
            if name in self._pools:
                return {"already": True,
                        "node": self._pools[name]["node"]}
            # reserve before the (slow) remote build so a concurrent serve
            # of the same name returns "already" instead of double-placing.
            # _recovering guards the build: the pump treats node=None as an
            # orphan, and without the flag it would concurrently re-place
            # this still-building pool on another node — leaking whichever
            # loop loses the race (the build is ~80 s on a cold TPU shape,
            # many pump periods long)
            entry = {"spec": dict(spec), "node": None,
                     "_recovering": True,
                     "next_rid": 0, "requests": {},
                     # client idempotency keys → rid: a client retrying a
                     # submit whose ACK was lost gets its ORIGINAL rid
                     # back instead of double-journaling (replicated with
                     # the journal so the dedupe survives failover)
                     "idem": {},
                     # per-pool WAL high-water: bumped on every
                     # replicate-worthy journal mutation; the standby and
                     # apply_pool_wal keep only strictly newer entries
                     "wal_seq": 0,
                     "done_total": 0, "failed_total": 0,
                     "cancelled_total": 0,
                     "shed_total": 0, "expired_total": 0,
                     # DistServe ledger (ISSUE 18): handoffs this pool
                     # PREFILLED for other pools' requests, keyed
                     # "{decode_pool}:{rid}" → state. Journaled so the
                     # ship edge is write-ahead in BOTH pools' WALs
                     # (the decode side rides its request row)
                     "handoffs": {},
                     "node_errors": [],
                     # measured service samples feeding the
                     # heterogeneous fair share: (seconds from
                     # submit to completion, new tokens)
                     "svc_samples": [],
                     "slots_now": int(spec.get("slots", _default_slots())),
                     "slots_cap": int(spec.get("slots", _default_slots())),
                     "slots_target_prev": None,
                     "t_last_resize": 0.0}
            self._pools[name] = entry
        # claim the scope at reservation time (not commit) so the gossiped
        # owner map converges while the ~80 s build runs; a failed build
        # leaves a harmless advisory claim (routing finds no pool)
        self._claim_scope(pool_scope(name))
        try:
            node = self._place()
            out = self._call(node, dict(spec, verb="lm_serve"),
                             timeout=self.build_rpc_timeout_s,
                             scope=pool_scope(name))
        except BaseException:
            with self._lock:
                # identity, not name: lm_stop + a re-serve may have
                # replaced the entry with a NEW generation mid-build —
                # deleting by name would destroy the newer reservation
                if self._pools.get(name) is entry:
                    del self._pools[name]
            raise
        with self._lock:
            # commit node + clear the build guard atomically, and only
            # into THIS build's entry: after lm_stop + re-serve the name
            # maps to a different generation whose build is still in
            # flight — committing into it would un-guard it mid-build
            if self._pools.get(name) is entry:
                entry["node"] = node
                entry["_recovering"] = False
                stale_node = None
            else:
                # stopped (or superseded) while the build RPC ran:
                # nothing must keep serving
                stale_node = node
        if stale_node is not None:
            self._stop_stale_loop(stale_node, name)
            return {"node": None, "stopped": True}
        return {"node": node, "slots": out.get("slots")}

    def _stop_stale_loop(self, node: str, name: str) -> None:
        """Best-effort lm_stop for a loop this manager just built but can
        no longer account for (the registry entry was stopped or re-placed
        while the build RPC ran) — an unaccounted live loop would decode
        into a dead outbox and hold device HBM indefinitely."""
        try:
            self._call(node, {"verb": "lm_stop", "name": name},
                       timeout=10.0, scope=pool_scope(name))
        except (TransportError, ValueError, OSError):
            pass

    def submit(self, name: str, prompt: list[int], max_new: int,
               temperature: float = 0.0, top_p: float = 1.0,
               top_k: int = 0, presence_penalty: float = 0.0,
               frequency_penalty: float = 0.0,
               stop: list[list[int]] | None = None,
               seed: int | None = None,
               tenant: str = "default", priority: str = "interactive",
               deadline_ms: float | None = None,
               idem_key: str | None = None,
               trace: tuple | None = None,
               handoff_from: str | None = None) -> int:
        """Journal a request (seed pinned NOW — replay after any failure
        must be token-exact even for sampled requests), then forward it to
        the pool's node. Forward failures leave it pending; the pump
        retries/relocates.

        ``handoff_from`` (DistServe, ISSUE 18) names a PREFILL replica
        that should fill the prompt's KV blocks and ship them to this
        pool's node before the forward — the journal entry carries the
        handoff state machine so a replay re-ships or falls back.

        QoS fields travel with the journal entry: the pool node's gateway
        decides admission at forward time, and a gateway shed comes back
        as a terminal journal state (never replayed). ``deadline_ms``
        bounds node-side queue wait measured from gateway admission — a
        replay after node death re-admits with a fresh deadline window."""
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, "
                             f"got {priority!r}")
        with self._lock:
            is_group = name in self._groups
        if is_group:
            return self._group_submit(
                name, prompt, max_new, temperature=temperature,
                top_p=top_p, top_k=top_k,
                presence_penalty=presence_penalty,
                frequency_penalty=frequency_penalty, stop=stop,
                seed=seed, tenant=tenant, priority=priority,
                deadline_ms=deadline_ms, idem_key=idem_key, trace=trace)
        with self._lock:
            pool = self._pools.get(name)
            if pool is None:
                raise ValueError(f"no managed pool {name!r}; "
                                 "lm_serve (placement=auto) first")
            if idem_key is not None:
                prior = pool.setdefault("idem", {}).get(idem_key)
                if prior is not None:
                    # client retry of an already-journaled submit (its ACK
                    # was lost): same booking, exactly-once — and the
                    # retried hop leaves a duplicate-marked span so the
                    # waterfall shows the dedupe
                    if self.spans is not None and trace:
                        self.spans.record(
                            "lm.submit", trace=trace[0], parent=trace[1],
                            attrs={"pool": name, "rid": int(prior),
                                   "duplicate": True})
                    return int(prior)
            rid = pool["next_rid"]
            pool["next_rid"] += 1
            tr = None
            if self.spans is not None:
                # mint/extend the trace at the journal booking: the ctx
                # rides the journal entry (and the standby snapshot), so
                # forwards — including post-adoption replays — chain
                # under this span
                sp = self.spans.record(
                    "lm.submit",
                    trace=trace[0] if trace else None,
                    parent=trace[1] if trace else None,
                    attrs={"pool": name, "rid": rid, "managed": True})
                tr = [sp.trace_id, sp.span_id]
            req = {"trace": tr,
                   "prompt": [int(t) for t in prompt],
                   "max_new": int(max_new),
                   "temperature": float(temperature),
                   "top_p": float(top_p),
                   "top_k": int(top_k),
                   "presence_penalty": float(presence_penalty),
                   "frequency_penalty": float(frequency_penalty),
                   "stop": ([[int(t) for t in q] for q in stop]
                            if stop else None),
                   "seed": int(seed) if seed is not None else rid,
                   "tenant": str(tenant), "priority": str(priority),
                   "deadline_ms": (float(deadline_ms)
                                   if deadline_ms is not None else None),
                   # flipped on the FIRST successful forward: a replay of
                   # an admitted request bypasses gateway admission
                   # (readmit) — the client was told it was in, recovery
                   # must not shed it
                   "admitted": False,
                   # DistServe state machine (ISSUE 18): prefilling →
                   # shipping → adopted, any failure → fallback (decode-
                   # side prefill). Journaled + replicated with the row.
                   "handoff": ({"from": str(handoff_from),
                                "state": "prefilling",
                                "shipped": 0, "bytes": 0}
                               if handoff_from is not None else None),
                   "status": _PENDING, "node_id": None,
                   "tokens": None, "prompt_len": None, "delivered": False,
                   "t_forwarded": None, "attempts": 0,
                   "t_submitted": self.wall()}
            pool["requests"][rid] = req
            if idem_key is not None:
                pool["idem"][idem_key] = rid
            node = pool["node"]
        if node is not None:
            if req.get("handoff"):
                self._handoff_ship(name, node, rid, req)
            self._forward(name, node, rid, req)
        # write-ahead the booking (and the forward's inflight/admitted
        # commit) to the standby's per-pool WAL segment: an adoption right
        # after this ack replays exactly this journal, per scope
        self._replicate_pool(name)
        return rid

    def _forward(self, name: str, node: str, rid: int,
                 req: dict[str, Any]) -> None:
        payload = {
            "verb": "lm_submit", "name": name,
            "prompt": req["prompt"], "max_new": req["max_new"],
            "temperature": req["temperature"],
            "top_p": req.get("top_p", 1.0),
            "top_k": req.get("top_k", 0),
            "presence_penalty": req.get("presence_penalty", 0.0),
            "frequency_penalty": req.get("frequency_penalty", 0.0),
            "stop": req.get("stop"),
            "seed": req["seed"],
            "tenant": req.get("tenant", "default"),
            "priority": req.get("priority", "interactive"),
            "deadline_ms": req.get("deadline_ms"),
            "readmit": bool(req.get("admitted")),
            # node-side dedupe for a LOST-REPLY retry: attempts counts
            # prior successful forwards, so the pump's re-forward after
            # a dropped ACK reuses the key (the node returns its
            # existing row), while a watchdog requeue — attempts
            # already bumped — gets a fresh key and books a fresh row
            "idem": f"{name}:{rid}:{req['attempts']}"}
        fsp = None
        tr = req.get("trace")
        if self.spans is not None and tr:
            # one span per forward ATTEMPT: a retried/re-placed request
            # shows every hop (and which node finally took it); the
            # stamped ctx makes the node's lm.submit span its child
            fsp = self.spans.start(
                "lm.forward", trace=tr[0], parent=tr[1],
                attrs={"pool": name, "rid": rid, "node": node,
                       "attempt": int(req.get("attempts", 0))})
            stamp_trace(payload, fsp.ctx)
        try:
            out = self._call(node, payload, scope=pool_scope(name))
        except (TransportError, OSError) as e:
            if fsp is not None:
                self.spans.finish(fsp, error=type(e).__name__)
            return                      # stays pending; pump will retry
        except ValueError as e:
            if fsp is not None:
                self.spans.finish(fsp, error=str(e)[:120])
            with self._lock:
                pool = self._pools.get(name)
                req2 = pool["requests"].get(rid) if pool else None
                if "no lm_serve pool" in str(e):
                    # the node is alive but has NO loop under this name
                    # (stale snapshot / out-of-band lm_stop): recoverable —
                    # orphan the pool so the pump re-establishes it, and
                    # leave the request pending for the resubmission
                    if pool is not None and pool["node"] == node:
                        self._orphan_pool_locked(name)
                elif "still starting" in str(e):
                    # transient: the node is mid-rebuild behind a _Starting
                    # reservation (e.g. an in-place resize); the request
                    # stays pending and the pump re-forwards once the new
                    # loop is up — failing it here would turn routine
                    # autoscaling into user-visible request failures
                    pass
                elif req2 is not None and req2["status"] == _PENDING:
                    reason = shed_reason(str(e))
                    if reason is not None:
                        # the pool's QoS gateway shed it (quota /
                        # queue_full / backpressure) — journal-terminal,
                        # exactly like a cancel: recovery must never
                        # resubmit a request the client was told is out
                        req2["status"] = _SHED
                        req2["shed_reason"] = reason
                        req2["error"] = str(e)
                        pool["shed_total"] += 1
                    else:
                        # the node REJECTED the request (validation) —
                        # permanent; retrying would loop forever. Surface
                        # via poll().
                        req2["status"] = _FAILED
                        req2["error"] = str(e)
                        pool["failed_total"] += 1
            return
        if fsp is not None:
            self.spans.finish(fsp, node_id=int(out["id"]),
                              duplicate=bool(out.get("duplicate")))
        cancel_on_node = False
        with self._lock:
            # recovery may have requeued/re-placed while the RPC ran; only
            # a still-pending request on the same node takes the mapping
            pool = self._pools.get(name)
            if pool is not None and pool["node"] == node:
                status = pool["requests"].get(rid, {}).get("status")
                if status == _PENDING:
                    req2 = pool["requests"][rid]
                    req2["status"] = _INFLIGHT
                    req2["node_id"] = int(out["id"])
                    req2["t_forwarded"] = self.wall()
                    req2["attempts"] += 1
                    req2["admitted"] = True
                elif status == _CANCELLED:
                    # cancel() raced this forward: it saw a pending
                    # request with no node mapping, so no node-side
                    # cancel was sent — send it now, or the node decodes
                    # all max_new tokens into a dropped completion
                    cancel_on_node = True
        if cancel_on_node:
            try:
                self._call(node, {"verb": "lm_cancel", "name": name,
                                  "id": int(out["id"])}, timeout=10.0,
                           scope=pool_scope(name))
            except (TransportError, ValueError, OSError):
                pass              # best-effort: the row burns out on its own

    def poll(self, name: str) -> dict[str, Any]:
        """Completions not yet handed to a client. Delivery to the CLIENT
        is at-most-once per completion (a poll reply lost in transit is not
        re-sent — the tokens remain reproducible from the journaled seed).
        Pruning is deferred to the NEXT poll, so the delivered flag lives
        through at least one journal-replication cycle and a standby that
        adopts between polls does not re-deliver or re-decode completions
        the old master already handed out (ADVICE r3)."""
        with self._lock:
            is_group = name in self._groups
        if is_group:
            return self._group_poll(name)
        with self._lock:
            pool = self._pools.get(name)
            if pool is None:
                raise ValueError(f"no managed pool {name!r}")
            # prune what the PREVIOUS poll delivered: the journal (and
            # every standby snapshot) stays bounded by requests in flight
            # plus one delivered batch
            pruned = set()
            for rid in [r for r, q in pool["requests"].items()
                        if q["delivered"]]:
                del pool["requests"][rid]
                pruned.add(rid)
            if pruned and pool.get("idem"):
                # idempotency keys age out with the requests they booked
                pool["idem"] = {k: r for k, r in pool["idem"].items()
                                if r not in pruned}
            out, errors, cancelled = [], [], []
            shed, expired = [], []
            for rid, req in sorted(pool["requests"].items()):
                if req["status"] == _DONE:
                    req["delivered"] = True
                    out.append({"id": rid, "tokens": req["tokens"],
                                "prompt_len": req["prompt_len"],
                                # same completion shape as the node-direct
                                # lm_poll reply (control.py)
                                "service_s": req.get("service_s", 0.0),
                                **({"logprobs": req["logprobs"]}
                                   if req.get("logprobs") is not None
                                   else {})})
                elif req["status"] == _FAILED:
                    req["delivered"] = True
                    errors.append(f"request {rid} failed: "
                                  f"{req.get('error', '?')}")
                elif req["status"] == _CANCELLED:
                    req["delivered"] = True
                    cancelled.append(rid)
                elif req["status"] == _SHED:
                    req["delivered"] = True
                    shed.append({"id": rid,
                                 "reason": req.get("shed_reason", "?")})
                elif req["status"] == _EXPIRED:
                    req["delivered"] = True
                    expired.append(rid)
        reply: dict[str, Any] = {"completions": out}
        if errors:
            reply["errors"] = errors
        if cancelled:
            reply["cancelled"] = cancelled
        if shed:
            reply["shed"] = shed
        if expired:
            reply["expired"] = expired
        return reply

    def cancel(self, name: str, rid: int) -> dict[str, Any]:
        """Cancel a journaled request. Terminal immediately in the journal
        (recovery and the pump will never replay it); if it was inflight,
        the node-side cancel is forwarded best-effort — the node's partial
        completion is dropped by `_drain` (its node_id mapping is gone).
        Client-facing: the id shows up in the next poll's ``cancelled``
        list. Returns {"cancelled": False} for ids already terminal or
        never journaled."""
        with self._lock:
            is_group = name in self._groups
            route = self._group_rid_locked(name, rid) if is_group else None
        if is_group:
            # an unmapped group id is already terminal (pruned) or was
            # never booked — same {"cancelled": False} as a plain pool
            return (self.cancel(*route) if route is not None
                    else {"cancelled": False})
        with self._lock:
            pool = self._pools.get(name)
            if pool is None:
                raise ValueError(f"no managed pool {name!r}")
            req = pool["requests"].get(rid)
            if req is None or req["status"] not in (_PENDING, _INFLIGHT):
                return {"cancelled": False}
            was_inflight = req["status"] == _INFLIGHT
            node, node_id = pool["node"], req["node_id"]
            req["status"] = _CANCELLED
            req["node_id"] = None
            pool["cancelled_total"] += 1
        # journal-terminal transition: write it ahead per pool so an
        # adoption never replays a request the client was told is out
        self._replicate_pool(name)
        if was_inflight and node is not None and node_id is not None:
            try:
                self._call(node, {"verb": "lm_cancel", "name": name,
                                  "id": int(node_id)}, timeout=10.0,
                           scope=pool_scope(name))
            except (TransportError, ValueError, OSError):
                pass          # best-effort: the row burns out on its own
        return {"cancelled": True}

    def partial(self, name: str) -> dict[str, Any]:
        """Streaming surface for a managed pool: the node's live-row
        progress mapped back to journal request ids. Rows the journal no
        longer tracks as inflight (just cancelled / just drained) are
        dropped — a client must never see an id it didn't submit."""
        with self._lock:
            is_group = name in self._groups
        if is_group:
            return self._group_partial(name)
        with self._lock:
            pool = self._pools.get(name)
            if pool is None:
                raise ValueError(f"no managed pool {name!r}")
            node = pool["node"]
            id_map = {r["node_id"]: rid
                      for rid, r in pool["requests"].items()
                      if r["status"] == _INFLIGHT
                      and r["node_id"] is not None}
            traces = {rid: r["trace"][0]
                      for rid, r in pool["requests"].items()
                      if r.get("trace")}
        if node is None:
            return {"partial": []}
        try:
            out = self._call(node, {"verb": "lm_partial", "name": name},
                             timeout=10.0)
        except (TransportError, ValueError, OSError) as e:
            return {"partial": [], "error": str(e)}
        rows = []
        for row in out.get("partial", ()):
            if int(row["id"]) not in id_map:
                continue
            rid = id_map[int(row["id"])]
            # journal trace id wins (it is the root the `trace` verb
            # resolves); the node row's own id is the fallback — and an
            # untraced request gains no `trace` key at all
            row = dict(row, id=rid)
            tr = traces.get(rid) or row.get("trace")
            if tr:
                row["trace"] = tr
            elif "trace" in row:
                del row["trace"]
            rows.append(row)
        reply = {"partial": rows}
        if out.get("sheds"):
            # recent gateway rejections with reasons (tenant-keyed, not
            # rid-keyed — a shed request never got a node id)
            reply["sheds"] = out["sheds"]
        return reply

    def stats(self, name: str) -> dict[str, Any]:
        with self._lock:
            is_group = name in self._groups
        if is_group:
            return self._group_stats(name)
        with self._lock:
            pool = self._pools.get(name)
            if pool is None:
                raise ValueError(f"no managed pool {name!r}")
            node = pool["node"]
            counts = {s: 0 for s in (_PENDING, _INFLIGHT)}
            for req in pool["requests"].values():
                if req["status"] in counts:
                    counts[req["status"]] += 1
            # terminal states are cumulative counters (delivered requests
            # are pruned from the journal)
            counts[_DONE] = pool["done_total"]
            counts[_FAILED] = pool["failed_total"]
            counts[_CANCELLED] = pool["cancelled_total"]
            counts[_SHED] = pool["shed_total"]
            counts[_EXPIRED] = pool["expired_total"]
            node_errors = list(pool["node_errors"][-5:])
        out = {"node": node, "journal": counts}
        if node_errors:
            out["node_errors"] = node_errors
        if node is not None:
            try:
                out["pool"] = self._call(
                    node, {"verb": "lm_stats", "name": name})["stats"]
            except (TransportError, ValueError, OSError) as e:
                out["pool_error"] = str(e)
        return out

    def qos(self, name: str) -> dict[str, Any]:
        """QoS observability for a managed pool: journal-side terminal
        counters plus the node gateway's live stats (None when the pool
        runs without a gateway or its node is unreachable). For a
        replica GROUP, the reply carries the group block (policy,
        replicas with roles/states, recent scaling decisions, tenant
        map) plus each replica's own qos."""
        with self._lock:
            is_group = name in self._groups
        if is_group:
            return self._group_qos(name)
        with self._lock:
            pool = self._pools.get(name)
            if pool is None:
                raise ValueError(f"no managed pool {name!r}")
            node = pool["node"]
            out: dict[str, Any] = {
                "node": node,
                "journal": {"shed": pool["shed_total"],
                            "expired": pool["expired_total"],
                            "cancelled": pool["cancelled_total"],
                            "done": pool["done_total"]}}
        if node is not None:
            try:
                out["qos"] = self._call(
                    node, {"verb": "lm_qos", "name": name},
                    timeout=10.0)["qos"]
            except (TransportError, ValueError, OSError) as e:
                out["qos_error"] = str(e)
        return out

    def prefix_op(self, verb: str, name: str,
                  p: dict[str, Any]) -> dict[str, Any]:
        """Relay a cluster-prefix verb (`prefix_publish`/`prefix_probe`/
        `prefix_fetch`) to a managed pool's serving node — prefix state
        lives in the pool's radix tree and SDFS memo, the journal only
        knows the spec. For a replica GROUP, publish/fetch fan over
        every active replica (counters summed — warming touches every
        replica's local tree) while probe asks one live replica (the
        published set is cluster-global, any replica sees it)."""
        fwd: dict[str, Any] = {"verb": verb}
        if p.get("tokens") is not None:
            fwd["tokens"] = [int(t) for t in p["tokens"]]
        if p.get("tenant") is not None:
            fwd["tenant"] = str(p["tenant"])
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                pool = self._pools.get(name)
                if pool is None:
                    raise ValueError(f"no managed pool {name!r}")
                targets = [(name, pool["node"])]
            else:
                targets = [(r, self._pools[r]["node"])
                           for r, m in sorted(g["replicas"].items())
                           if m["state"] == "active"
                           and r in self._pools]
        targets = [(r, n) for r, n in targets if n is not None]
        if not targets:
            raise ValueError(f"{name!r}: no serving node for {verb}")
        if verb == "prefix_probe" or len(targets) == 1:
            rname, node = targets[0]
            return self._call(node, dict(fwd, name=rname),
                              scope=pool_scope(name))
        merged: dict[str, Any] = {"replicas": 0}
        for rname, node in targets:
            try:
                out = self._call(node, dict(fwd, name=rname),
                                 scope=pool_scope(name))
            except (TransportError, ValueError, OSError) as e:
                merged.setdefault("errors", []).append(
                    f"{rname}: {e}")
                continue
            merged["replicas"] += 1
            for k, v in out.items():
                if isinstance(v, (int, float)) and not isinstance(
                        v, bool):
                    merged[k] = merged.get(k, 0) + v
                elif k not in merged:
                    merged[k] = v
        return merged

    # -- DistServe KV handoff (ISSUE 18) -----------------------------------

    def kv_handoff(self, name: str, p: dict[str, Any]) -> dict[str, Any]:
        """Relay a client-initiated ``kv_handoff`` verb to a managed
        pool's serving node — like ``prefix_op``, the block/radix state
        lives on the node, the journal only knows the spec. A replica
        GROUP resolves to its first active replica (any replica can probe
        or ship; the manager's own routed handoffs pick replicas via
        ``_route_group_locked``, this path is the debugging/ops surface).
        A ship must orchestrate FROM the prefill replica's own host: its
        loop owns the exported blocks."""
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                pool = self._pools.get(name)
                if pool is None:
                    raise ValueError(f"no managed pool {name!r}")
                targets = [(name, pool["node"])]
            else:
                targets = [(r, self._pools[r]["node"])
                           for r, m in sorted(g["replicas"].items())
                           if m["state"] == "active" and r in self._pools]
        targets = [(r, n) for r, n in targets if n is not None]
        if not targets:
            raise ValueError(f"{name!r}: no serving node for kv_handoff")
        rname, node = targets[0]
        fwd = {k: v for k, v in p.items()
               if k in ("verb", "op", "tokens", "blobs", "from_depth",
                        "start_depth", "target_host", "target_name",
                        "timeout")}
        return self._call(node, dict(fwd, name=rname),
                          scope=pool_scope(name))

    def _handoff_ship(self, name: str, node: str, rid: int,
                      req: dict[str, Any]) -> None:
        """DistServe handoff leg: have the journaled PREFILL replica fill
        the prompt's KV blocks and ship them point-to-point to the decode
        pool's node BEFORE the request forwards there — the decode
        admission then hits the grafted radix chain and prefills only the
        trailing remainder (zero re-prefill for shipped blocks).

        State machine, write-ahead at every edge in BOTH pools' WALs:

            prefilling → shipping → adopted      (happy path)
                                  ↘ fallback     (any failure — decode-
                                                  side prefill, request
                                                  untouched)

        Death semantics: a manager death at prefilling/shipping replays
        the ship from the adopted journal (the pump re-runs this for
        pending rows with a non-terminal handoff state — safe because
        kv_handoff is naturally idempotent: re-probe + dedup grafts). A
        PREFILL-replica death fails the ship RPC after retries →
        fallback. A DECODE-replica death orphans the pool; re-placement
        resets adopted → prefilling (`_orphan_pool_locked`: the new node
        holds no blocks) and the recovery re-ships to the new node. The
        handoff is an optimization layered UNDER the journal: it never
        completes, fails, or doubles a request by itself."""
        hop = req.get("handoff") or {}
        pre_rname = hop.get("from")
        key = f"{name}:{rid}"
        with self._lock:
            pool = self._pools.get(name)
            live = pool["requests"].get(rid) if pool else None
            lhop = (live or {}).get("handoff")
            if (live is None or live["status"] != _PENDING
                    or not lhop
                    or lhop.get("state") in ("adopted", "fallback")):
                return
            pre = self._pools.get(pre_rname)
            pre_node = pre["node"] if pre is not None else None
            lhop["state"] = "shipping"
            if pre is not None:
                ledger = pre.setdefault("handoffs", {})
                ledger[key] = "shipping"
                # bounded ledger: oldest entries age out first (terminal
                # states carry no replay value; live ones are re-entered
                # by the pump from the decode side anyway)
                while len(ledger) > 128:
                    del ledger[next(iter(ledger))]
        if pre_node is None or pre_node == node:
            # prefill replica unplaced/gone, or colocated with the target
            # (same node serves both loops: its blocks are already local
            # only in the prefill POOL's tree, not the decode pool's — a
            # self-ship over loopback still works, but a colocated pair
            # means the role split degenerated; just prefill in place)
            self._handoff_done(name, rid, pre_rname, "fallback")
            return
        # write-ahead the SHIPPING edge to both scopes' WAL segments
        # before the RPC: an adopter replays the ship, never wonders
        # whether it ran (idempotent either way)
        self._replicate_pool(name)
        if pre_rname != name:
            self._replicate_pool(pre_rname)
        payload = {"verb": "kv_handoff", "op": "ship", "name": pre_rname,
                   "target_host": node, "target_name": name,
                   "tokens": list(req["prompt"])}
        sp = None
        tr = req.get("trace")
        if self.spans is not None and tr:
            sp = self.spans.start(
                "lm.handoff_ship", trace=tr[0], parent=tr[1],
                attrs={"pool": name, "rid": rid, "prefill": pre_rname,
                       "node": pre_node})
            stamp_trace(payload, sp.ctx)
        t_ship = self.wall()
        try:
            out = call_with_retry(
                lambda: self._call(pre_node, payload,
                                   scope=pool_scope(pre_rname)))
        except (TransportError, OSError, ValueError) as e:
            if sp is not None:
                self.spans.finish(sp, error=str(e)[:120], fallback=True)
            if self.service is not None:
                self.service.metrics.record_counter("kv_handoff_fallbacks")
            self._handoff_done(name, rid, pre_rname, "fallback")
            return
        if sp is not None:
            self.spans.finish(sp, shipped=int(out.get("shipped", 0)),
                              bytes=int(out.get("bytes", 0)))
        # measured-TTFT feed (ISSUE 20 satellite): the ship wall time IS
        # the prefill latency the decode replica skipped
        self._observe_ttft(pre_rname, self.wall() - t_ship)
        self._handoff_done(name, rid, pre_rname, "adopted",
                           shipped=int(out.get("shipped", 0)),
                           nbytes=int(out.get("bytes", 0)))

    def _handoff_done(self, name: str, rid: int, pre_rname: str | None,
                      state: str, shipped: int = 0,
                      nbytes: int = 0) -> None:
        """Commit a terminal handoff edge to both journals + WALs."""
        key = f"{name}:{rid}"
        with self._lock:
            pool = self._pools.get(name)
            live = pool["requests"].get(rid) if pool else None
            hop = (live or {}).get("handoff")
            if hop is not None:
                hop["state"] = state
                hop["shipped"] = int(shipped)
                hop["bytes"] = int(nbytes)
            pre = (self._pools.get(pre_rname)
                   if pre_rname is not None else None)
            if pre is not None and key in pre.get("handoffs", {}):
                pre["handoffs"][key] = state
        if pool is not None:
            self._replicate_pool(name)
        if pre is not None and pre_rname != name:
            self._replicate_pool(pre_rname)

    def stop(self, name: str) -> dict[str, Any]:
        with self._lock:
            is_group = name in self._groups
        if is_group:
            return self._group_stop(name)
        with self._lock:
            pool = self._pools.pop(name, None)
        if pool is None:
            return {"stopped": False}
        if pool["node"] is not None:
            try:
                self._call(pool["node"], {"verb": "lm_stop", "name": name},
                           scope=pool_scope(name))
            except (TransportError, ValueError, OSError):
                pass                    # node may already be dead
        return {"stopped": True}

    def managed_pools(self) -> list[str]:
        with self._lock:
            return sorted(set(self._pools) | set(self._groups))

    def has_pool(self, name: str) -> bool:
        # groups answer too: _route_cluster (serve/control.py) routes a
        # group-addressed verb through this manager exactly like a pool
        with self._lock:
            return name in self._pools or name in self._groups

    def trace_of(self, name: str, rid: int) -> str | None:
        """Trace id of a journaled request (None once pruned/untraced) —
        the `trace` control verb's lookup for managed pools."""
        with self._lock:
            route = self._group_rid_locked(name, rid)
        if route is not None:
            return self.trace_of(*route)
        with self._lock:
            pool = self._pools.get(name)
            if pool is None:
                return None
            tr = (pool["requests"].get(int(rid)) or {}).get("trace")
            return tr[0] if tr else None

    # -- replica pool groups (serve/autoscaler.py) -------------------------
    #
    # A group is routing + scaling state over ordinary managed pools
    # named "{group}@r{i}". All mechanism lives here (spawn / drain /
    # retire / rebalance as journaled, epoch-stamped decisions); the
    # POLICY — when to do which — lives in the Autoscaler's tick.

    def _as_now(self) -> float:
        """Group timing (dwell, drain windows, decision stamps) runs on
        the autoscaler's injectable clock, so fake-clock tests and the
        chaos harness drive it deterministically."""
        return float(self.autoscaler.clock())

    def group_names(self) -> list[str]:
        with self._lock:
            return sorted(self._groups)

    def has_group(self, name: str) -> bool:
        with self._lock:
            return name in self._groups

    def _group_rid_locked(self, name: str, rid: int):
        """(replica, replica-rid) for a group request id; None when the
        name is not a group or the id is unmapped. Caller holds the
        lock."""
        g = self._groups.get(name)
        if g is None:
            return None
        ent = g["rid_map"].get(int(rid))
        return (ent[0], int(ent[1])) if ent is not None else None

    @staticmethod
    def _tenant_weight_fn(g: dict[str, Any]):
        """WFQ weight lookup from the group spec's gateway quotas — the
        same weights serve/gateway.py fair-queues with; 1.0 default."""
        gw = g["spec"].get("gateway") or {}
        tq = gw.get("tenants") or {}
        try:
            default_w = float((gw.get("default") or {}).get("weight", 1.0))
        except (TypeError, ValueError):
            default_w = 1.0

        def weight(t: str) -> float:
            try:
                return max(float((tq.get(t) or {}).get(
                    "weight", default_w)), 1e-6)
            except (TypeError, ValueError):
                return 1.0

        return weight

    def _group_debts_locked(self, g: dict[str, Any],
                            replicas: list[str]) -> dict[str, float]:
        """WFQ debt per replica: outstanding (pending+inflight) journal
        entries weighted by 1/tenant-weight."""
        weight = self._tenant_weight_fn(g)
        debts: dict[str, float] = {}
        for r in replicas:
            pool = self._pools.get(r)
            debt = 0.0
            if pool is not None:
                for req in pool["requests"].values():
                    if req["status"] in (_PENDING, _INFLIGHT):
                        debt += 1.0 / weight(req.get("tenant", "default"))
            debts[r] = round(debt, 6)
        return debts

    def _record_decision_locked(self, name: str, g: dict[str, Any],
                                action: str, dwell: bool = True,
                                **attrs) -> dict[str, Any]:
        """Append a scaling decision to the group's journal: seq'd,
        epoch-stamped (a deposed master's decisions are refused with its
        whole managed journal — _route_cluster), span-recorded. ``dwell``
        False (policy updates) leaves the scaling damper untouched."""
        seq = g["next_seq"]
        g["next_seq"] += 1
        d: dict[str, Any] = {
            "seq": seq, "epoch": list(self.membership.epoch.view()),
            "action": action, "t": round(self._as_now(), 6), **attrs}
        g["decisions"].append(d)
        del g["decisions"][:-128]          # bounded journal window
        if dwell:
            g["t_last_decision"] = self._as_now()
        if self.spans is not None:
            sp = self.spans.record(
                f"autoscale.{action}",
                attrs={"group": name,
                       **{k: v for k, v in d.items()
                          if k in ("seq", "replica", "role", "tenant",
                                   "src", "dst", "p95")}})
            d["trace"] = [sp.trace_id, sp.span_id]
        return d

    def _replicate_scale(self, name: str,
                         decision: dict[str, Any] | None) -> None:
        """Push the decision — with the group's full wire entry — to the
        standby between snapshots (FailoverManager.wal_scale, mirroring
        the CNN task WAL): an adoption right after a scaling action must
        replay it exactly, not rediscover it."""
        fo = self.failover
        if fo is None or decision is None:
            return
        with self._lock:
            g = self._groups.get(name)
            entry = self._group_wire_locked(g) if g is not None else None
        if entry is not None:
            fo.wal_scale(name, decision, entry)

    def _serve_group(self, spec: dict[str, Any],
                     auto: Any) -> dict[str, Any]:
        """Create a replica group from an lm_serve spec carrying
        ``autoscale={...}`` and spawn its min_replicas decode replicas."""
        policy = AutoscalePolicy.from_config(
            self.config, auto if isinstance(auto, dict) else None)
        name = spec["name"]
        with self._lock:
            if name in self._groups:
                return {"already": True, "group": True,
                        "replicas": sorted(self._groups[name]["replicas"])}
            if name in self._pools:
                raise ValueError(f"{name!r} already names a managed pool")
            self._groups[name] = {
                "spec": dict(spec), "policy": policy.to_wire(),
                "replicas": {}, "next_replica": 0,
                "tenants": {}, "next_grid": 0, "rid_map": {},
                "idem": {}, "decisions": [], "next_seq": 0,
                "t_last_decision": 0.0,
                # prefill-heavy admission fraction since group creation:
                # feeds the autoscaler's role-split spawn choice.
                # "handoff" counts the prefill-heavy subset served in
                # DistServe handoff mode (ISSUE 18)
                "route_counts": {"total": 0, "prefill": 0, "handoff": 0}}
        self._claim_scope(pool_scope(name))
        spawned = []
        for _ in range(policy.min_replicas):
            d = self.group_spawn(name, role="decode")
            if d is not None:
                spawned.append(d["replica"])
        if not spawned:
            with self._lock:
                # nothing placed — withdraw so the caller's retry starts
                # clean instead of finding a zero-replica husk
                self._groups.pop(name, None)
            raise ValueError(
                f"group {name!r}: could not place any replica")
        return {"group": True, "node": None, "replicas": spawned}

    def group_spawn(self, name: str, role: str = "decode",
                    **attrs) -> dict[str, Any] | None:
        """Spawn one replica pool. Deterministic journaled names
        ("{group}@r{i}" via next_replica) are the spawn idempotency
        backstop: serve() answers "already" for an existing name, so a
        replayed spawn can never double-place (chaos invariant)."""
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                return None
            policy = AutoscalePolicy.from_wire(g["policy"])
            active = [r for r, m in g["replicas"].items()
                      if m["state"] == "active"]
            if len(active) >= policy.max_replicas:
                return None
            rname = f"{name}@r{g['next_replica']}"
            g["next_replica"] += 1
            rspec = dict(g["spec"], name=rname)
            # replica pools are named "{group}@r{i}" but must load the
            # GROUP's stored model — carry it explicitly (node-side
            # lm_serve loads p["model"] over the pool name)
            rspec.setdefault("model", name)
            if role == "prefill" and policy.prefill_chunk > 0:
                # DistServe's split, request-routing grained: the prefill
                # replica takes long-prompt admissions with chunked
                # prefill tuned on (Sarathi interleave, PR 7)
                rspec["prefill_chunk"] = int(policy.prefill_chunk)
        try:
            out = self.serve(rspec)
        except (TransportError, ValueError, OSError):
            return None        # autoscaler retries on a later tick
        with self._lock:
            g = self._groups.get(name)
            stale = g is None
            warm_tenants: list[str] = []
            if not stale:
                g["replicas"][rname] = {"role": role, "state": "active",
                                        "t_drain": 0.0}
                decision = self._record_decision_locked(
                    name, g, "spawn", replica=rname, role=role,
                    node=out.get("node"), **attrs)
                if g["spec"].get("cluster_prefix"):
                    warm_tenants = sorted(g["tenants"])
        if stale:
            self.stop(rname)   # group stopped mid-build: nothing serves
            return None
        self._replicate_scale(name, decision)
        if warm_tenants and out.get("node") is not None:
            self._warm_replica(name, rname, out["node"], warm_tenants)
        return decision

    def _warm_replica(self, group: str, rname: str, node: str,
                      tenants: list[str]) -> None:
        """Warm-at-spawn (ISSUE 17): a fresh replica of a cluster-prefix
        group fetches the published chains of the group's known tenants
        before traffic lands on it, so its first request for a published
        prefix prefills only the suffix. Best-effort — a warm failure
        never fails the spawn (the replica just starts cold, exactly
        like before this feature existed)."""
        for tenant in tenants:
            try:
                self._call(node, {"verb": "prefix_fetch", "name": rname,
                                  "tenant": tenant},
                           scope=pool_scope(group))
            except (TransportError, ValueError, OSError):
                pass

    @staticmethod
    def _replica_index(rname: str) -> int:
        try:
            return int(rname.rsplit("@r", 1)[1])
        except (IndexError, ValueError):
            return -1

    def group_retire_start(self, name: str, replica: str | None = None,
                           **attrs) -> dict[str, Any] | None:
        """Mark a replica DRAINING: it takes no new routing but keeps
        serving — and delivering — its journal. Default victim: the
        newest active replica. Its pinned tenants re-route by debt on
        their next submit."""
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                return None
            active = [r for r, m in g["replicas"].items()
                      if m["state"] == "active"]
            if len(active) <= 1:
                return None     # never drain the last live replica
            victim = replica if replica is not None else max(
                active, key=self._replica_index)
            m = g["replicas"].get(victim)
            if m is None or m["state"] != "active":
                return None
            m["state"] = "draining"
            m["t_drain"] = self._as_now()
            g["tenants"] = {t: r for t, r in g["tenants"].items()
                            if r != victim}
            decision = self._record_decision_locked(
                name, g, "retire_start", replica=victim, **attrs)
        self._replicate_scale(name, decision)
        return decision

    def group_retire(self, name: str, replica: str,
                     **attrs) -> dict[str, Any] | None:
        """Remove a DRAINED replica and stop its pool — only when every
        journaled request on it has been DELIVERED (zero admitted-
        request loss); the autoscaler additionally waits out
        drain_window_s before calling this."""
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                return None
            m = g["replicas"].get(replica)
            if m is None or m["state"] != "draining":
                return None
            pool = self._pools.get(replica)
            if pool is not None and any(
                    not r["delivered"]
                    for r in pool["requests"].values()):
                return None     # still owes the client work — keep it
            del g["replicas"][replica]
            g["rid_map"] = {grid: ent for grid, ent
                            in g["rid_map"].items()
                            if ent[0] != replica}
            decision = self._record_decision_locked(
                name, g, "retire", replica=replica, **attrs)
        self.stop(replica)
        self._replicate_scale(name, decision)
        return decision

    def group_rebalance(self, name: str, **attrs) -> dict[str, Any] | None:
        """Move the heaviest-debt tenant on the max-WFQ-debt decode
        replica to the min-debt one. New submissions only — outstanding
        work stays where it was journaled."""
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                return None
            policy = AutoscalePolicy.from_wire(g["policy"])
            decode = [r for r, m in g["replicas"].items()
                      if m["state"] == "active"
                      and m["role"] == "decode"]
            if len(decode) < 2:
                return None
            debts = self._group_debts_locked(g, decode)
            hi = max(decode, key=lambda r: (debts[r], r))
            lo = min(decode, key=lambda r: (debts[r], r))
            if debts[hi] - debts[lo] <= policy.rebalance_debt:
                return None
            weight = self._tenant_weight_fn(g)
            per_tenant: dict[str, float] = {}
            pool = self._pools.get(hi)
            if pool is not None:
                for req in pool["requests"].values():
                    if req["status"] in (_PENDING, _INFLIGHT):
                        t = req.get("tenant", "default")
                        if g["tenants"].get(t) == hi:
                            per_tenant[t] = (per_tenant.get(t, 0.0)
                                             + 1.0 / weight(t))
            if not per_tenant:
                return None     # debt is unpinned traffic; nothing to move
            tenant = max(per_tenant, key=lambda t: (per_tenant[t], t))
            g["tenants"][tenant] = lo
            decision = self._record_decision_locked(
                name, g, "rebalance", tenant=tenant, src=hi, dst=lo,
                debt_gap=round(debts[hi] - debts[lo], 4), **attrs)
        self._replicate_scale(name, decision)
        return decision

    def _route_group_locked(self, g: dict[str, Any], prompt_len: int,
                            tenant: str) -> tuple[str, str | None]:
        """Replica for a new admission, as ``(target, handoff_from)``.

        Prefill-heavy prompts (length >= prefill_len_threshold —
        serve/admission.py:is_prefill_heavy) with an active prefill
        replica go one of two ways (ISSUE 18):

        - **handoff mode** (the group also has an active DECODE replica
          and the spec carries a KV block pool): the request is routed
          to its tenant-sticky decode replica, and ``handoff_from``
          names the prefill replica that will fill + ship the KV blocks
          there first (``_handoff_ship``) — true DistServe, the decode
          replica never pays the prefill.
        - **whole-request mode** (no block pool, or prefill-only
          group): the prefill replica serves the request end to end,
          the pre-ISSUE-18 behavior.

        Everything else is tenant-sticky on decode replicas, new tenants
        landing on the least-WFQ-debt one.

        Gray-failure defense (ISSUE 20): replicas placed on QUARANTINED
        nodes (membership/health.py) are skipped — including a tenant's
        sticky assignment, which re-pins by debt on its next submit —
        unless every placed replica is quarantined, where availability
        wins and routing falls back to the full set. Among multiple
        prefill replicas the one with the lowest measured ship-time EWMA
        (``_ttft_ewma``, fed by ``_handoff_ship``) takes the admission;
        with no samples the order is unchanged (lowest replica index)."""
        from idunno_tpu.serve.admission import is_prefill_heavy
        policy = AutoscalePolicy.from_wire(g["policy"])
        active = sorted((r for r, m in g["replicas"].items()
                         if m["state"] == "active"
                         and r in self._pools),
                        key=self._replica_index)
        quarantined = self._quarantined_hosts()
        if quarantined:
            healthy = [r for r in active
                       if (self._pools.get(r) or {}).get("node")
                       not in quarantined]
            if healthy and len(healthy) < len(active):
                if self.service is not None:
                    self.service.metrics.record_counter(
                        "quarantine_reroutes", len(active) - len(healthy))
                active = healthy
        if not active:
            # transient mid-scale (every replica draining/unplaced):
            # land on any placed replica rather than failing the submit
            active = sorted((r for r in g["replicas"]
                             if r in self._pools),
                            key=self._replica_index)
        if not active:
            raise ValueError(
                f"group {g['spec'].get('name')!r} has no placed "
                "replica yet; still starting; retry shortly")
        g["route_counts"]["total"] += 1
        decode = [r for r in active
                  if g["replicas"][r]["role"] == "decode"] or active

        def sticky() -> str:
            assigned = g["tenants"].get(tenant)
            if assigned in decode:
                return assigned
            debts = self._group_debts_locked(g, decode)
            target = min(decode, key=lambda r: (debts[r], r))
            g["tenants"][tenant] = target
            return target

        if is_prefill_heavy(prompt_len, policy.prefill_len_threshold):
            g["route_counts"]["prefill"] += 1
            pre = [r for r in active
                   if g["replicas"][r]["role"] == "prefill"]
            if len(pre) > 1:
                # measured-TTFT routing (ISSUE 20 satellite): soft-state
                # ship-time EWMAs; unsampled replicas sort as 0.0 so they
                # attract traffic until measured, and with no samples at
                # all the key degenerates to the replica index — the
                # pre-EWMA order
                pre.sort(key=lambda r: (
                    self._ttft_ewma.get(r, (0.0, 0))[0],
                    self._replica_index(r)))
            has_decode = any(g["replicas"][r]["role"] == "decode"
                             for r in active)
            if pre and has_decode \
                    and int(g["spec"].get("kv_block_size") or 0) > 0:
                g["route_counts"]["handoff"] = (
                    g["route_counts"].get("handoff", 0) + 1)
                return sticky(), pre[0]
            if pre:
                return pre[0], None
        return sticky(), None

    def _observe_ttft(self, replica: str, seconds: float) -> None:
        """Record one measured prefill ship time for a prefill replica.
        Manager-local soft state (NOT journaled/wired): after failover
        the adopter simply starts cold and routing degrades to the
        replica-index order until it re-measures."""
        with self._lock:
            ewma, n = self._ttft_ewma.get(replica, (0.0, 0))
            ewma = seconds if n == 0 else 0.7 * ewma + 0.3 * seconds
            self._ttft_ewma[replica] = (ewma, n + 1)

    def _group_submit(self, name: str, prompt: list[int], max_new: int,
                      *, temperature: float, top_p: float, top_k: int,
                      presence_penalty: float, frequency_penalty: float,
                      stop: list[list[int]] | None, seed: int | None,
                      tenant: str, priority: str,
                      deadline_ms: float | None, idem_key: str | None,
                      trace: tuple | None) -> int:
        """Route a group submission to a replica and book the group-level
        id mapping. Group ids are their own sequence (next_grid); the
        seed defaults to the GROUP id so a post-failover replay is
        token-exact no matter which replica re-serves it."""
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                raise ValueError(f"no managed pool {name!r}; "
                                 "lm_serve (placement=auto) first")
            if idem_key is not None:
                prior = g["idem"].get(idem_key)
                if prior is not None:
                    return int(prior)
            rname, pre_rname = self._route_group_locked(
                g, len(prompt), str(tenant))
            grid = g["next_grid"]
            g["next_grid"] += 1
            if idem_key is not None:
                g["idem"][idem_key] = grid
        try:
            rid = self.submit(
                rname, prompt, max_new, temperature=temperature,
                top_p=top_p, top_k=top_k,
                presence_penalty=presence_penalty,
                frequency_penalty=frequency_penalty, stop=stop,
                seed=seed if seed is not None else grid,
                tenant=tenant, priority=priority,
                deadline_ms=deadline_ms, idem_key=None, trace=trace,
                handoff_from=pre_rname)
        except BaseException:
            with self._lock:
                g2 = self._groups.get(name)
                if (g2 is not None and idem_key is not None
                        and g2["idem"].get(idem_key) == grid):
                    del g2["idem"][idem_key]
            raise
        with self._lock:
            g2 = self._groups.get(name)
            if g2 is not None:
                # [replica, replica-rid, delivered]
                g2["rid_map"][grid] = [rname, rid, False]
        return grid

    def _group_poll(self, name: str) -> dict[str, Any]:
        """Merge every replica's poll, remapping ids to group ids. Same
        deferred-prune discipline as the pool poll: a mapping delivered
        now survives one more replication cycle before pruning."""
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                raise ValueError(f"no managed pool {name!r}")
            pruned = {grid for grid, ent in g["rid_map"].items()
                      if ent[2]}
            for grid in pruned:
                del g["rid_map"][grid]
            if pruned and g["idem"]:
                g["idem"] = {k: v for k, v in g["idem"].items()
                             if v not in pruned}
            replicas = sorted(g["replicas"], key=self._replica_index)
            rev = {(ent[0], int(ent[1])): grid
                   for grid, ent in g["rid_map"].items()}
        merged: dict[str, Any] = {"completions": []}
        delivered: set[int] = set()

        def remap(r: str, rid: int) -> int | None:
            grid = rev.get((r, int(rid)))
            if grid is not None:
                delivered.add(grid)
            return grid

        for r in replicas:
            try:
                out = self.poll(r)
            except ValueError:
                continue      # replica not placed yet / just retired
            for c in out.get("completions", ()):
                grid = remap(r, c["id"])
                if grid is not None:
                    merged["completions"].append(dict(c, id=grid))
            for e in out.get("errors", ()):
                m = _ERR_RE.match(str(e))
                grid = remap(r, int(m.group(1))) if m else None
                if grid is not None:
                    merged.setdefault("errors", []).append(
                        f"request {grid} failed: {m.group(2)}")
                elif not m:
                    merged.setdefault("errors", []).append(f"{r}: {e}")
            for key in ("cancelled", "expired"):
                for rid in out.get(key, ()):
                    grid = remap(r, rid)
                    if grid is not None:
                        merged.setdefault(key, []).append(grid)
            for s in out.get("shed", ()):
                grid = remap(r, s["id"])
                if grid is not None:
                    merged.setdefault("shed", []).append(
                        dict(s, id=grid))
        if delivered:
            with self._lock:
                g2 = self._groups.get(name)
                if g2 is not None:
                    for grid in delivered:
                        ent = g2["rid_map"].get(grid)
                        if ent is not None:
                            ent[2] = True
        return merged

    def _group_partial(self, name: str) -> dict[str, Any]:
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                raise ValueError(f"no managed pool {name!r}")
            replicas = sorted(g["replicas"], key=self._replica_index)
            rev = {(ent[0], int(ent[1])): grid
                   for grid, ent in g["rid_map"].items()}
        rows, sheds = [], []
        for r in replicas:
            try:
                out = self.partial(r)
            except ValueError:
                continue
            for row in out.get("partial", ()):
                grid = rev.get((r, int(row["id"])))
                if grid is not None:
                    rows.append(dict(row, id=grid, replica=r))
            sheds.extend(out.get("sheds", ()))
        reply: dict[str, Any] = {"partial": rows}
        if sheds:
            reply["sheds"] = sheds
        return reply

    def _group_stats(self, name: str) -> dict[str, Any]:
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                raise ValueError(f"no managed pool {name!r}")
            meta = {r: dict(m) for r, m in g["replicas"].items()}
        out: dict[str, Any] = {"group": True, "replicas": {}}
        journal: dict[str, int] = {}
        for r in sorted(meta, key=self._replica_index):
            try:
                st = self.stats(r)
            except ValueError:
                continue
            out["replicas"][r] = dict(st, role=meta[r]["role"],
                                      state=meta[r]["state"])
            for k, v in st.get("journal", {}).items():
                journal[k] = journal.get(k, 0) + int(v)
        out["journal"] = journal
        with self._lock:
            g = self._groups.get(name)
            if g is not None:
                out["tenants"] = dict(g["tenants"])
                out["route_counts"] = dict(g["route_counts"])
        return out

    def _group_qos(self, name: str) -> dict[str, Any]:
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                raise ValueError(f"no managed pool {name!r}")
            group_block = {
                "policy": dict(g["policy"]),
                "replicas": {r: dict(m)
                             for r, m in g["replicas"].items()},
                "tenants": dict(g["tenants"]),
                "route_counts": dict(g["route_counts"]),
                "decisions": [dict(d) for d in g["decisions"][-10:]],
                "decisions_total": g["next_seq"]}
            replicas = sorted(g["replicas"], key=self._replica_index)
        # forecast gauges (ISSUE 18): the predictive scale-ahead's view
        # of this group — predicted arrival rate + spawns it triggered
        group_block["forecast"] = self.autoscaler.forecast_view(name)
        out: dict[str, Any] = {"group": group_block, "replicas": {}}
        for r in replicas:
            try:
                out["replicas"][r] = self.qos(r)
            except ValueError:
                pass
        return out

    def _group_stop(self, name: str) -> dict[str, Any]:
        with self._lock:
            g = self._groups.pop(name, None)
        if g is None:
            return {"stopped": False}
        replicas = sorted(g["replicas"], key=self._replica_index)
        for r in replicas:
            self.stop(r)
        return {"stopped": True, "replicas": replicas}

    def autoscale_get(self, name: str) -> dict[str, Any]:
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                raise ValueError(f"no replica group {name!r}")
            return {"policy": dict(g["policy"]),
                    "replicas": {r: dict(m)
                                 for r, m in g["replicas"].items()},
                    "decisions": [dict(d) for d in g["decisions"][-20:]],
                    "decisions_total": g["next_seq"]}

    def autoscale_set(self, name: str,
                      updates: dict[str, Any]) -> dict[str, Any]:
        """Update the group's policy (the lm_autoscale verb). Journaled
        as a (dwell-exempt) decision, so failover replays the policy
        exactly like any other scaling state."""
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                raise ValueError(f"no replica group {name!r}")
            policy = AutoscalePolicy.from_wire(g["policy"]).merged(
                dict(updates))
            g["policy"] = policy.to_wire()
            decision = self._record_decision_locked(
                name, g, "policy", dwell=False, policy=policy.to_wire())
        self._replicate_scale(name, decision)
        return {"policy": policy.to_wire()}

    def group_view(self, name: str) -> dict[str, Any] | None:
        """Consistent read-only snapshot for one autoscaler tick: parsed
        policy, per-replica state/role/drain-time plus the UNDELIVERED
        journal count (the retire gate), the dwell anchor, route counts
        and current WFQ debts. None when the group doesn't exist."""
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                return None
            replicas: dict[str, Any] = {}
            for r, m in g["replicas"].items():
                pool = self._pools.get(r)
                undelivered = 0
                if pool is not None:
                    undelivered = sum(
                        1 for q in pool["requests"].values()
                        if not q["delivered"])
                replicas[r] = {"state": m["state"], "role": m["role"],
                               "t_drain": m["t_drain"],
                               "undelivered": undelivered,
                               "node": (pool or {}).get("node")}
            decode = [r for r, m in g["replicas"].items()
                      if m["state"] == "active" and m["role"] == "decode"]
            return {"policy": AutoscalePolicy.from_wire(g["policy"]),
                    "replicas": replicas,
                    "t_last_decision": g["t_last_decision"],
                    "route_counts": dict(g["route_counts"]),
                    "debts": self._group_debts_locked(g, decode)}

    def group_gauges(self, name: str) -> dict[str, Any]:
        """Live per-replica gauges for the autoscaler: the node
        gateway's interactive p95 queue wait (the Clockwork SLO signal)
        + its sample count, and the journal backlog. An unreachable or
        gateway-less replica reports n=0 — no samples can never trigger
        a scale-out."""
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                return {}
            targets = []
            for r, m in g["replicas"].items():
                if m["state"] != "active":
                    continue
                pool = self._pools.get(r)
                node = pool["node"] if pool is not None else None
                backlog = 0
                if pool is not None:
                    backlog = sum(
                        1 for q in pool["requests"].values()
                        if q["status"] in (_PENDING, _INFLIGHT))
                targets.append((r, node, backlog))
        out: dict[str, Any] = {}
        for r, node, backlog in targets:
            p95, n = 0.0, 0
            admitted: dict[str, int] = {}
            if node is not None:
                try:
                    qos = self._call(
                        node, {"verb": "lm_qos", "name": r},
                        timeout=10.0).get("qos")
                except (TransportError, ValueError, OSError):
                    qos = None
                classes = (qos or {}).get("classes") or {}
                w = (classes.get("interactive") or {}).get(
                    "queue_wait_s") or {}
                p95 = float(w.get("p95", 0.0))
                n = int(w.get("n", 0))
                # cumulative per-class admissions: the predictive
                # scale-ahead's arrival-rate signal (ISSUE 18)
                admitted = {c: int((cls or {}).get("admitted", 0))
                            for c, cls in classes.items()}
                # service-level health feed (ISSUE 20): the replica's
                # interactive p95 lands in the differential ledger as a
                # second breach channel beside raw RPC latency (the
                # ledger ignores it until a transport activated it)
                if n > 0 and p95 > 0.0:
                    health = getattr(self.membership, "health", None)
                    if health is not None:
                        health.observe_service(node, p95)
            out[r] = {"interactive_p95": p95, "n": n,
                      "backlog": backlog, "admitted": admitted}
        return out

    def _ensure_group_replicas(self) -> None:
        """Re-establish group replicas an adopted snapshot predated: an
        ACTIVE replica with no pool entry is re-served from the group
        spec (serve() is name-idempotent, so this can never double-
        place — the chaos invariant); a DRAINING one with no pool has no
        journal left to drain and retires."""
        with self._lock:
            missing, finished = [], []
            for name, g in self._groups.items():
                policy = AutoscalePolicy.from_wire(g["policy"])
                for r, m in g["replicas"].items():
                    if r in self._pools:
                        continue
                    if m["state"] == "active":
                        rspec = dict(g["spec"], name=r)
                        rspec.setdefault("model", name)
                        if (m["role"] == "prefill"
                                and policy.prefill_chunk > 0):
                            rspec["prefill_chunk"] = int(
                                policy.prefill_chunk)
                        missing.append(rspec)
                    else:
                        finished.append((name, r))
        for rspec in missing:
            try:
                self.serve(rspec)
            except (TransportError, ValueError, OSError):
                pass            # pump retries next period
        for name, r in finished:
            self.group_retire(name, r)

    @staticmethod
    def _group_from_wire(d: dict[str, Any]) -> dict[str, Any]:
        return {"spec": dict(d["spec"]), "policy": dict(d["policy"]),
                "replicas": {r: dict(m) for r, m
                             in d.get("replicas", {}).items()},
                "next_replica": int(d.get("next_replica", 0)),
                "tenants": dict(d.get("tenants", {})),
                "next_grid": int(d.get("next_grid", 0)),
                "rid_map": {int(grid): list(ent) for grid, ent
                            in d.get("rid_map", {}).items()},
                "idem": {k: int(v) for k, v
                         in d.get("idem", {}).items()},
                "decisions": [dict(x) for x in d.get("decisions", ())],
                "next_seq": int(d.get("next_seq", 0)),
                "t_last_decision": float(d.get("t_last_decision", 0.0)),
                "route_counts": dict(d.get(
                    "route_counts", {"total": 0, "prefill": 0}))}

    def _group_wire_locked(self, g: dict[str, Any]) -> dict[str, Any]:
        return {"spec": dict(g["spec"]), "policy": dict(g["policy"]),
                "replicas": {r: dict(m)
                             for r, m in g["replicas"].items()},
                "next_replica": int(g["next_replica"]),
                "tenants": dict(g["tenants"]),
                "next_grid": int(g["next_grid"]),
                "rid_map": {str(grid): list(ent)
                            for grid, ent in g["rid_map"].items()},
                "idem": dict(g["idem"]),
                "decisions": [dict(d) for d in g["decisions"]],
                "next_seq": int(g["next_seq"]),
                "t_last_decision": float(g["t_last_decision"]),
                "route_counts": dict(g["route_counts"])}

    def apply_scale_wal(self, deltas: dict[str, Any],
                        keep_scope=None) -> None:
        """Adoption-time replay of scale-WAL deltas (failover.py). Each
        delta carries the group's full wire entry at decision time;
        apply any strictly newer than the adopted snapshot — the
        decision journal is append-only, so 'newer' is just a longer
        log (next_seq). ``keep_scope`` filters to the group scopes this
        host actually adopts (scope-scoped adoption, ISSUE 15)."""
        with self._lock:
            for name, d in sorted(deltas.items()):
                entry = d.get("entry")
                if not entry:
                    continue
                if keep_scope is not None \
                        and not keep_scope(pool_scope(name)):
                    continue
                cur = self._groups.get(name)
                if (cur is None or int(cur["next_seq"])
                        < int(entry.get("next_seq", 0))):
                    self._groups[name] = self._group_from_wire(entry)

    # -- train jobs --------------------------------------------------------

    def train(self, spec: dict[str, Any]) -> dict[str, Any]:
        """Place a training job on the least-loaded alive node; on that
        node's death the job restarts on a survivor with resume=True,
        continuing from its last store checkpoint."""
        spec = {k: v for k, v in spec.items()
                if k not in ("verb", "placement", "local", "resume")}
        name = spec["name"]
        with self._lock:
            job = self._jobs.get(name)
            if job is not None and not self._job_over(job):
                raise ValueError(f"training job {name!r} already running "
                                 f"on {job['node']}")
            # _recovering guards the initial build exactly as in serve():
            # without it the pump sees node=None mid-build and _recover_job
            # starts a SECOND copy of the job (resume=True) on another
            # node — two jobs burning two chips, one unaccounted
            entry = {"spec": dict(spec), "node": None,
                     "_recovering": True,
                     "status": None, "stop_requested": False}
            self._jobs[name] = entry
        try:
            node = self._place()
            self._call(node, dict(spec, verb="train_start"),
                       timeout=self.build_rpc_timeout_s)
        except BaseException:
            with self._lock:
                # identity, not name (see serve()): a replaced-generation
                # entry must not be destroyed by this build's cleanup
                if self._jobs.get(name) is entry:
                    del self._jobs[name]
            raise
        with self._lock:
            # commit node + clear the build guard atomically, and only
            # into THIS build's entry (as serve()): after a stop + re-train
            # the name maps to a new generation still mid-build
            if self._jobs.get(name) is entry:
                entry["node"] = node
                entry["_recovering"] = False
                stale_node = None
            else:
                stale_node = node
        if stale_node is not None:
            # the job this build started answers to nobody — stop it
            # (best-effort; a chip-burning unaccounted trainer otherwise)
            try:
                self._call(stale_node, {"verb": "train_stop",
                                        "name": name}, timeout=10.0)
            except (TransportError, ValueError, OSError):
                pass
            return {"started": False, "stopped": True, "node": None}
        return {"started": True, "node": node}

    def train_status(self, name: str) -> dict[str, Any]:
        with self._lock:
            job = self._jobs.get(name)
            if job is None:
                raise ValueError(f"no managed training job {name!r}")
            node, cached = job["node"], job["status"]
        if node is not None:
            try:
                st = self._call(node, {"verb": "train_status",
                                       "name": name})
                with self._lock:
                    if name in self._jobs:
                        self._jobs[name]["status"] = st
                return dict(st, node=node)
            except (TransportError, ValueError, OSError):
                pass
        return dict(cached or {}, node=node, stale=True)

    def train_stop(self, name: str) -> dict[str, Any]:
        """Record the stop intent FIRST (so a dead/unreachable node can
        never turn an explicit stop into an auto-resume), then best-effort
        stop the node-local job; the pump retries unconfirmed stops."""
        with self._lock:
            job = self._jobs.get(name)
            if job is None:
                return {"stopped": False}
            job["stop_requested"] = True
            node = job["node"]
        out: dict[str, Any] = {"stopped": True}
        if node is not None:
            try:
                out = self._call(node, {"verb": "train_stop",
                                        "name": name})
                out["stopped"] = True
            except (TransportError, ValueError, OSError) as e:
                out["pending"] = f"node {node} unreachable ({e}); " \
                                 "stop is recorded and will be retried"
        with self._lock:
            if name in self._jobs and out.get("status"):
                self._jobs[name]["status"] = out["status"]
        return out

    def has_job(self, name: str) -> bool:
        with self._lock:
            return name in self._jobs

    # -- pump: runs on the acting master's master loop ---------------------

    def pump_once(self) -> None:
        """Forward pending requests, drain completions, refresh job
        status. All RPCs outside the lock.

        Multi-owner gate (ISSUE 15): any host holding pool scopes pumps
        ITS pools/groups — scope owners are full control planes for their
        journals, not passive standbys. Train jobs and the cluster-wide
        fair share stay acting-master duties (they arbitrate the shared
        CNN+LM capacity, which has exactly one arbiter)."""
        master = self.membership.is_acting_master
        self._step_down_moved_scopes()
        now = self.wall()
        with self._lock:
            has_lm = bool(self._pools or self._groups)
        if not master and not has_lm:
            return
        with self._lock:
            for pool in self._pools.values():
                self._requeue_stale_locked(pool, now)
            pools = {n: (p["node"],
                         [(rid, dict(r)) for rid, r in
                          sorted(p["requests"].items())
                          if r["status"] == _PENDING])
                     for n, p in self._pools.items()}
            jobs = ([(n, j["node"]) for n, j in self._jobs.items()
                     if not self._job_over(j)] if master else [])
            # stop-requested jobs whose node never confirmed: retry the
            # stop (the job may still be burning its node's chip)
            stop_retries = [
                (n, j["node"]) for n, j in self._jobs.items()
                if j.get("stop_requested") and j["node"] is not None
                and not ((j.get("status") or {}).get("stopped")
                         or (j.get("status") or {}).get("done")
                         or (j.get("status") or {}).get("error"))] \
                if master else []
        for name, (node, pending) in pools.items():
            if node is None:
                self._recover_pool(name)
                continue
            for rid, req in pending:
                ho = req.get("handoff")
                if ho and ho.get("state") in ("prefilling", "shipping"):
                    # replay-or-fallback: a death (ours or a peer's) mid-
                    # handoff left the journaled state non-terminal — the
                    # ship is idempotent, re-run it before the forward
                    self._handoff_ship(name, node, rid, req)
                self._forward(name, node, rid, req)
            self._drain(name, node)
        for name, node in jobs:
            if node is None:
                self._recover_job(name)
                continue
            try:
                st = self._call(node, {"verb": "train_status",
                                       "name": name}, timeout=10.0)
            except (TransportError, ValueError, OSError):
                continue
            with self._lock:
                if name in self._jobs:
                    self._jobs[name]["status"] = st
        for name, node in stop_retries:
            try:
                out = self._call(node, {"verb": "train_stop",
                                        "name": name}, timeout=10.0)
            except (TransportError, ValueError, OSError):
                continue
            with self._lock:
                if name in self._jobs and out.get("status"):
                    self._jobs[name]["status"] = out["status"]
        with self._lock:
            have_groups = bool(self._groups)
        if have_groups:
            # replica-group upkeep + the closed capacity loop — both run
            # only here, so they inherit the owner/master gate above
            self._ensure_group_replicas()
            self.autoscaler.tick()
        if master:
            self._update_fair_share()

    # -- heterogeneous fair share (round-2 VERDICT item 4) -----------------

    @staticmethod
    def _avg_request_s(pool: dict[str, Any]) -> float:
        s = pool["svc_samples"]
        return sum(x for x, _ in s) / len(s) if s else 0.0

    def allocation_view(self) -> dict[str, Any]:
        """c1/c2-style arbitration report: measured per-unit seconds and
        the fair worker-unit share for every live job — CNN query jobs
        (avg seconds per query) and LM decode pools (avg seconds per
        request, per-token breakdown included) — via the reference ratio
        formula generalized over the job union
        (`scheduler/fair.py:heterogeneous_shares`)."""
        from idunno_tpu.scheduler.fair import heterogeneous_shares

        n_workers = len(self.membership.members.alive_hosts())
        sched = self.service.scheduler if self.service else None
        cnn = {}
        if sched is not None:
            cnn = {m: sched.avg_query_time.get(m, 0.0)
                   for m in sched.active_models()}
        with self._lock:
            lm = {n: self._avg_request_s(p)
                  for n, p in self._pools.items()
                  if p["node"] is not None}
            tok = {n: (sum(s for s, _ in p["svc_samples"])
                       / max(sum(t for _, t in p["svc_samples"]), 1))
                   for n, p in self._pools.items() if p["svc_samples"]}
            slots = {n: p["slots_now"] for n, p in self._pools.items()}
        shares = heterogeneous_shares(cnn, lm, self.config.rate_factor,
                                      n_workers)
        jobs: dict[str, Any] = {}
        for m, t in cnn.items():
            jobs[f"cnn:{m}"] = {"avg_query_s": round(t, 4),
                                "share": shares.get(f"cnn:{m}", 0)}
        for n, t in lm.items():
            jobs[f"lm:{n}"] = {"avg_request_s": round(t, 4),
                               "avg_token_s": round(tok.get(n, 0.0), 5),
                               "share": shares.get(f"lm:{n}", 0),
                               "slots": slots.get(n)}
        return {"rate_factor": self.config.rate_factor,
                "n_workers": n_workers, "jobs": jobs}

    def _update_fair_share(self) -> None:
        """Apply the arbitration: feed each pool's measured per-request
        seconds into the CNN scheduler (whose assign() then computes
        shares over the job UNION, shrinking CNN worker counts while
        pools run), and resize each pool's slots toward its fair FRACTION
        of its own slot capacity. Slots are per-device batch rows, not
        workers, so the absolute worker-clamped share is the wrong scale
        (ADVICE r3: a lone 16-slot pool on a 1-node cluster must keep 16
        slots, not shrink to 1); a pool with no competing job keeps its
        full spec untouched. A resize rebuilds the pool (recompile), so it
        needs the same target on two consecutive pumps (hysteresis), a
        ``resize_dwell_s`` gap since the last applied resize (a rate
        hovering on a share boundary must not thrash), and can be pinned
        off per pool with spec ``fixed_slots=True``."""
        if self.service is None:
            return
        with self._lock:
            rates = {n: self._avg_request_s(p)
                     for n, p in self._pools.items()
                     if p["node"] is not None}
        self.service.scheduler.extra_jobs = {
            f"lm:{n}": t for n, t in rates.items()}
        if not rates:
            return
        view = self.allocation_view()
        jobs = view["jobs"]
        total_share = sum(j["share"] for j in jobs.values()) or 1
        now = self.wall()
        resize = []
        with self._lock:
            for name, pool in self._pools.items():
                job = jobs.get(f"lm:{name}")
                if (job is None or pool["node"] is None
                        or pool["spec"].get("fixed_slots")):
                    continue
                if len(jobs) == 1:
                    # the only measured job in the cluster — nothing to
                    # arbitrate against; full user-specced capacity
                    target = pool["slots_cap"]
                else:
                    # slots_cap is the user's spec — the pool may shrink
                    # below it while other jobs run and grow back, never
                    # beyond
                    frac = job["share"] / total_share
                    target = max(1, min(pool["slots_cap"],
                                        round(frac * pool["slots_cap"])))
                if (target != pool["slots_now"]
                        and target == pool["slots_target_prev"]
                        and now - pool.get("t_last_resize", 0.0)
                        >= self.resize_dwell_s):
                    resize.append((name, pool["node"], target))
                pool["slots_target_prev"] = target
        for name, node, target in resize:
            self._resize_pool(name, node, target)

    def _resize_pool(self, name: str, node: str, target: int) -> None:
        """Rebuild a resized pool IN PLACE on its current node:
        ``lm_serve reload=True`` makes the node stop the old serving loop
        before starting the new one, so nothing keeps decoding into a
        dead outbox or holding HBM (ADVICE r3 — re-placing via the
        recovery path could land on a DIFFERENT node and leak the old
        node's live loop). The manager's slot bookkeeping commits only
        AFTER the node confirms the rebuild — a bail-out (concurrent
        recovery, a racing build's _Starting reservation answering
        "already", node failure) must leave manager and node agreeing on
        the OLD slot count, with the hysteresis free to retry. Only if
        the node itself fails does this fall back to orphan + recovery."""
        with self._lock:
            entry = self._pools.get(name)
            if (entry is None or entry["node"] != node
                    or entry.get("_recovering")):
                return
            entry["_recovering"] = True
            spec = dict(entry["spec"], slots=target)
        try:
            try:
                out = self._call(node, dict(spec, verb="lm_serve",
                                            reload=True),
                                 timeout=self.build_rpc_timeout_s,
                                 scope=pool_scope(name))
            except (TransportError, ValueError, OSError):
                with self._lock:
                    if (self._pools.get(name) is entry
                            and entry["node"] == node):
                        self._orphan_pool_locked(name)
                return                  # pump re-places on a survivor
            if out.get("already") or out.get("stopped"):
                # 'already': a racing build holds the name's _Starting
                # reservation; 'stopped': an lm_stop won the race mid-
                # build and the fresh loop was immediately torn down. In
                # both cases nothing is serving the NEW slot count — keep
                # the old bookkeeping everywhere and let a later pump
                # (or the stop) settle it
                return
            with self._lock:
                # identity check: stopped (or replaced by a re-serve
                # generation) while the rebuild RPC ran means the fresh
                # loop answers to nobody — stop it (an lm_stop that landed
                # mid-build was already handled by the 'stopped' reply)
                stale = (self._pools.get(name) is not entry
                         or entry["node"] != node)
                if not stale:
                    entry["spec"]["slots"] = target
                    entry["slots_now"] = target
                    entry["t_last_resize"] = self.wall()
                    # the replaced loop dropped its in-flight requests;
                    # requeue for token-exact replay. attempts reset: a
                    # pool-level rebuild (and its recompile) must not
                    # consume a request's suspicion budget (ADVICE r3)
                    for req in entry["requests"].values():
                        if req["status"] == _INFLIGHT:
                            req["status"] = _PENDING
                            req["node_id"] = None
                            req["attempts"] = 0
                    pending = [(rid, dict(r)) for rid, r in
                               sorted(entry["requests"].items())
                               if r["status"] == _PENDING]
            if stale:
                self._stop_stale_loop(node, name)
                return
            for rid, req in pending:
                self._forward(name, node, rid, req)
        finally:
            with self._lock:
                # clear only THIS generation's guard: a replacement
                # entry's in-flight build must stay guarded
                if self._pools.get(name) is entry:
                    entry["_recovering"] = False

    def _requeue_stale_locked(self, pool: dict[str, Any],
                              now: float) -> None:
        """Watchdog: an inflight request can wedge without its node dying
        (the node's error list is a destructive read a failed poll can
        consume; a drained lm_poll reply can be lost to a timeout).
        Requeue anything inflight past its effective timeout — the base
        ``request_timeout_s`` stretched by the request's own expected
        decode time at the pool's measured per-token rate PLUS the
        expected node-side queue wait for the pool's current backlog
        (service-time samples no longer bake queue wait in, so the
        watchdog must model it: a large max_new behind a deep queue, or
        a from-scratch recompile after recovery, is slow with nothing
        wrong — ADVICE r3). FAIL after max_request_attempts forwards."""
        s = pool["svc_samples"]
        tok_s = (sum(x for x, _ in s) / max(sum(t for _, t in s), 1)
                 if s else 0.0)
        per_req_s = self._avg_request_s(pool)
        # no completions yet = no measured rate to stretch with, but the
        # FIRST requests are exactly the ones paying the from-scratch
        # compile — grant the build allowance instead of the bare base
        first_req_grace = 0.0 if s else self.build_rpc_timeout_s
        n_inflight = sum(1 for r in pool["requests"].values()
                         if r["status"] == _INFLIGHT)
        slots = max(int(pool.get("slots_now", 1)), 1)
        backlog_wait = per_req_s * (n_inflight / slots)
        for rid, req in pool["requests"].items():
            if req["status"] != _INFLIGHT:
                continue
            eff = (self.request_timeout_s + first_req_grace
                   + self.request_timeout_slack * (
                       req["max_new"] * tok_s + backlog_wait))
            if now - (req["t_forwarded"] or now) < eff:
                continue
            if req["attempts"] >= self.max_request_attempts:
                req["status"] = _FAILED
                req["error"] = (f"no completion after {req['attempts']} "
                                f"forwards x {eff:.0f}s")
                pool["failed_total"] += 1
            else:
                req["status"] = _PENDING
                req["node_id"] = None

    def _drain(self, name: str, node: str) -> None:
        # scoped: draining CONSUMES the node outbox (ownership transfers
        # to the poller), so a deposed pool owner must be fenced here or
        # it would steal completions the scope's new owner journals
        try:
            out = self._call(node, {"verb": "lm_poll", "name": name},
                             timeout=10.0, scope=pool_scope(name))
        except (TransportError, ValueError, OSError):
            return
        if not (out.get("completions") or out.get("errors")):
            return
        with self._lock:
            pool = self._pools.get(name)
            if pool is None or pool["node"] != node:
                return                  # stopped or re-placed mid-drain
            for e in out.get("errors", ()):
                # node-side loop errors are request-anonymous; keep them
                # for stats/debugging (the watchdog above unsticks any
                # request they wedged)
                if len(pool["node_errors"]) < 100:
                    pool["node_errors"].append(str(e))
            by_node_id = {r["node_id"]: r
                          for r in pool["requests"].values()
                          if r["status"] == _INFLIGHT}
            now = self.wall()
            for c in out.get("completions", ()):
                req = by_node_id.get(int(c["id"]))
                if req is not None:
                    if c.get("cancelled"):
                        # out-of-band node-side cancel (a local=True
                        # lm_cancel bypassing this manager): journal it as
                        # cancelled, and keep its partial service time out
                        # of the fair-share samples
                        req["status"] = _CANCELLED
                        req["node_id"] = None
                        pool["cancelled_total"] += 1
                        continue
                    if c.get("rejected") == "expired":
                        # the deadline passed in the gateway queue —
                        # journal-terminal (never replayed), no service
                        # sample: the request never reached a slot
                        req["status"] = _EXPIRED
                        req["node_id"] = None
                        pool["expired_total"] += 1
                        continue
                    req["status"] = _DONE
                    req["tokens"] = [int(t) for t in c["tokens"]]
                    req["prompt_len"] = int(c["prompt_len"])
                    if c.get("logprobs") is not None:
                        req["logprobs"] = [float(x)
                                           for x in c["logprobs"]]
                    req["service_s"] = round(
                        float(c.get("service_s", 0.0)), 6)
                    req["node_id"] = None
                    pool["done_total"] += 1
                    new_toks = len(req["tokens"]) - req["prompt_len"]
                    # fair-share signal: node-measured SERVICE time (slot
                    # admission → retirement), not master-side sojourn — a
                    # backlogged pool must not measure slower and grow its
                    # own share (round-3 VERDICT weak #4; the reference
                    # normalizes processing time, not queue time,
                    # `mp4_machinelearning.py:656-674`). Sojourn fallback
                    # only for a node predating the field.
                    svc = float(c.get("service_s", 0.0))
                    if svc <= 0.0:
                        svc = now - req["t_submitted"]
                    # cold-start completions funded the pool's one-time
                    # compiles (VERDICT item 4): their service time is
                    # capacity planning, not steady-state cost — keep
                    # them out of the fair-share/autoscaler demand signal
                    # (a warmup=True pool never produces one)
                    if not c.get("cold_start"):
                        pool["svc_samples"].append((svc, max(new_toks, 1)))
                        del pool["svc_samples"][:-32]    # rolling window
        # drained completions are unrecoverable from the node — write the
        # terminal transitions ahead so an adoption between here and the
        # next snapshot re-delivers instead of re-decoding
        self._replicate_pool(name)

    # -- recovery ----------------------------------------------------------

    def _on_member_change(self, host: str, old, new) -> None:
        if new is not MemberStatus.LEAVE:
            return
        # multi-owner gate (ISSUE 15): every manager holding pools — the
        # acting master AND every scope owner — recovers its own placed
        # nodes; a non-master owner must not strand a dead pool node
        if not (self.membership.is_acting_master
                or self._scope_names_nonempty()):
            return
        with self._lock:
            dead_pools = [n for n, p in self._pools.items()
                          if p["node"] == host]
            for n in dead_pools:
                self._orphan_pool_locked(n)
            dead_jobs = [n for n, j in self._jobs.items()
                         if j["node"] == host and not self._job_over(j)]
            for n in dead_jobs:
                self._jobs[n]["node"] = None
        if not (dead_pools or dead_jobs):
            return

        # re-place off-thread: this callback runs on the membership monitor
        # loop, and a pool rebuild (store fetch + device alloc) must not
        # stall failure detection for other hosts. pump_once retries any
        # recovery that fails here.
        def _recover():
            for n in dead_pools:
                self._recover_pool(n)
            for n in dead_jobs:
                self._recover_job(n)

        threading.Thread(target=_recover, daemon=True,
                         name=f"{self.host}-lm-recover").start()

    def _orphan_pool_locked(self, name: str) -> None:
        pool = self._pools[name]
        pool["node"] = None
        for req in pool["requests"].values():
            if req["status"] == _INFLIGHT:
                req["status"] = _PENDING
                req["node_id"] = None
                # pool-level requeue: the request did nothing wrong, and
                # the recovery rebuild's recompile must not eat into its
                # per-request suspicion budget (ADVICE r3)
                req["attempts"] = 0
            # a handoff adopted INTO the dead node is gone with it: the
            # re-placed pool holds no blocks, so re-enter the state
            # machine (the recovery re-ships to the new node; fallback
            # rows stay terminal — the prefill side already failed once)
            hop = req.get("handoff")
            if (hop and req["status"] == _PENDING
                    and hop.get("state") in ("shipping", "adopted")):
                hop["state"] = "prefilling"

    def _recover_pool(self, name: str) -> None:
        """Re-establish an orphaned pool on a survivor and resubmit every
        unfinished request (token-exact: seeds were pinned at admission).

        Prefix-cache pools recover the same way: kv_block_size /
        kv_cache_blocks ride the journaled spec, so the rebuilt pool has
        the same paged-cache config but an EMPTY radix tree — resubmitted
        requests cold-miss and recompute their own KV (never replaying
        another node's blocks), keeping the token-exactness contract
        (`tests/test_prefix_cache.py` rebuild test).

        Serialized per pool: the membership-change thread, the adoption
        thread and the pump can all reach here concurrently, and a second
        ``lm_serve reload=True`` landing on the same node would replace
        the first recovery's freshly built loop — stranding its
        just-forwarded requests as inflight ids of a dead loop until the
        watchdog times them out."""
        with self._lock:
            entry = self._pools.get(name)
            if (entry is None or entry["node"] is not None
                    or entry.get("_recovering")):
                return
            entry["_recovering"] = True
            spec = dict(entry["spec"])
        try:
            try:
                node = self._place()
                self._call(node, dict(spec, verb="lm_serve", reload=True),
                           timeout=self.build_rpc_timeout_s,
                           scope=pool_scope(name))
            except (TransportError, ValueError, OSError):
                return                  # pump retries next period
            with self._lock:
                # identity check: stopped, or replaced by a re-serve
                # generation (whose own build must not be committed into
                # or un-guarded by this recovery), while the rebuild RPC
                # ran — the fresh loop answers to nobody, stop it
                stale = (self._pools.get(name) is not entry
                         or entry["node"] is not None)
                if not stale:
                    entry["node"] = node
                    pending = [(rid, dict(r)) for rid, r in
                               sorted(entry["requests"].items())
                               if r["status"] == _PENDING]
            if stale:
                self._stop_stale_loop(node, name)
                return
            for rid, req in pending:
                ho = req.get("handoff")
                if ho and ho.get("state") in ("prefilling", "shipping"):
                    self._handoff_ship(name, node, rid, req)
                self._forward(name, node, rid, req)
        finally:
            with self._lock:
                # clear only THIS generation's guard
                if self._pools.get(name) is entry:
                    entry["_recovering"] = False

    def _recover_job(self, name: str) -> None:
        with self._lock:
            entry = self._jobs.get(name)
            if (entry is None or entry["node"] is not None
                    or entry.get("_recovering")):
                return
            entry["_recovering"] = True   # serialized like _recover_pool
            spec = dict(entry["spec"], resume=True)
        try:
            try:
                node = self._place()
                self._call(node, dict(spec, verb="train_start"),
                           timeout=self.build_rpc_timeout_s)
            except (TransportError, ValueError, OSError):
                return
            stale_node = None
            with self._lock:
                # identity check, as _recover_pool: a stop + re-train may
                # have replaced the entry mid-rebuild
                if (self._jobs.get(name) is entry
                        and entry["node"] is None
                        and not entry.get("stop_requested")):
                    entry["node"] = node
                else:
                    stale_node = node
            if stale_node is not None:
                try:
                    self._call(stale_node, {"verb": "train_stop",
                                            "name": name}, timeout=10.0)
                except (TransportError, ValueError, OSError):
                    pass
        finally:
            with self._lock:
                # clear only THIS generation's guard
                if self._jobs.get(name) is entry:
                    entry["_recovering"] = False

    # -- failover replication ---------------------------------------------

    @staticmethod
    def _pool_wire(p: dict[str, Any]) -> dict[str, Any]:
        """Wire form of one pool's registry entry + journal — the unit
        the periodic snapshot AND the per-pool WAL replicate."""
        return {"spec": dict(p["spec"]), "node": p["node"],
                "next_rid": p["next_rid"],
                "wal_seq": int(p.get("wal_seq", 0)),
                "done_total": p["done_total"],
                "failed_total": p["failed_total"],
                "cancelled_total": p["cancelled_total"],
                "shed_total": p["shed_total"],
                "expired_total": p["expired_total"],
                "svc_samples": [list(s) for s in p["svc_samples"]],
                "slots_now": p["slots_now"],
                "slots_cap": p["slots_cap"],
                "idem": dict(p.get("idem", {})),
                "handoffs": dict(p.get("handoffs", {})),
                "requests": {str(rid): dict(r) for rid, r
                             in p["requests"].items()}}

    @staticmethod
    def _pool_from_wire(p: dict[str, Any]) -> dict[str, Any]:
        return {"spec": dict(p["spec"]), "node": p["node"],
                "next_rid": int(p["next_rid"]),
                "wal_seq": int(p.get("wal_seq", 0)),
                "done_total": int(p.get("done_total", 0)),
                "failed_total": int(p.get("failed_total", 0)),
                "cancelled_total": int(p.get("cancelled_total", 0)),
                "shed_total": int(p.get("shed_total", 0)),
                "expired_total": int(p.get("expired_total", 0)),
                "node_errors": [],
                "svc_samples": [tuple(s) for s
                                in p.get("svc_samples", ())],
                "slots_now": int(p.get(
                    "slots_now",
                    p["spec"].get("slots", _default_slots()))),
                "slots_cap": int(p.get(
                    "slots_cap",
                    p["spec"].get("slots", _default_slots()))),
                "slots_target_prev": None,
                "t_last_resize": 0.0,
                "idem": {k: int(v) for k, v
                         in p.get("idem", {}).items()},
                "handoffs": {str(k): str(v) for k, v
                             in p.get("handoffs", {}).items()},
                # defaults first: a snapshot from an older master may
                # predate the watchdog/measurement fields
                "requests": {int(rid): {"t_forwarded": None,
                                        "attempts": 0, "top_p": 1.0,
                                        "top_k": 0,
                                        "t_submitted": 0.0,
                                        "tenant": "default",
                                        "priority": "interactive",
                                        "deadline_ms": None,
                                        "admitted": False,
                                        "handoff": None,
                                        "trace": None, **dict(r)}
                             for rid, r in p["requests"].items()}}

    @staticmethod
    def _pool_delta(base: dict[str, Any],
                    cur: dict[str, Any]) -> dict[str, Any]:
        """Delta frame between two wire entries: changed scalar fields +
        changed/removed request rows since the standby's acked base.
        Linear in the mutation, not the journal depth — the full-entry
        ship was quadratic at depth (ISSUE 15 satellite)."""
        fields = {k: v for k, v in cur.items()
                  if k not in ("requests", "idem") and base.get(k) != v}
        breq, creq = base.get("requests", {}), cur.get("requests", {})
        frame = {"delta": True,
                 "base_seq": int(base.get("wal_seq", 0)),
                 "wal_seq": int(cur.get("wal_seq", 0)),
                 "fields": fields,
                 "changed": {rid: req for rid, req in creq.items()
                             if breq.get(rid) != req},
                 "removed": [rid for rid in breq if rid not in creq]}
        if cur.get("idem") != base.get("idem"):
            frame["idem"] = dict(cur.get("idem", {}))
        return frame

    @staticmethod
    def _truncate_wire(entry: dict[str, Any]) \
            -> tuple[dict[str, Any], int]:
        """Compact a wire entry below the delivered LOW-WATER MARK: the
        contiguous run of rids from the bottom of the journal whose rows
        are all journal-terminal AND delivered carries no recovery value
        (an adopter neither resubmits terminal rows nor re-delivers
        delivered ones — poll() will prune them on its next call anyway)
        so the shipped WAL segment drops them, with their idem keys,
        instead of re-shipping them on every mutation (ISSUE 17
        satellite). Only the prefix below the first live/undelivered rid
        truncates — the segment stays a contiguous journal tail, and the
        `need_full` fallback stays correct across a truncated base: a
        delta against the truncated base lists later truncations as
        ``removed`` rows, and any base gap re-ships the (truncated) full
        entry. Returns (entry, rows_truncated); the input is untouched
        when nothing truncates."""
        reqs = entry["requests"]
        live = [int(rid) for rid, q in reqs.items()
                if q["status"] in (_PENDING, _INFLIGHT)
                or not q.get("delivered")]
        lwm = min(live) if live else int(entry["next_rid"])
        drop = {rid for rid in reqs if int(rid) < lwm}
        if not drop:
            return entry, 0
        entry = dict(entry)
        entry["requests"] = {rid: q for rid, q in reqs.items()
                             if rid not in drop}
        dropped = {int(rid) for rid in drop}
        if entry.get("idem"):
            entry["idem"] = {k: v for k, v in entry["idem"].items()
                             if int(v) not in dropped}
        return entry, len(drop)

    def _replicate_pool(self, name: str) -> None:
        """Push the pool's journal mutation to its scope standby's WAL
        segment (FailoverManager.wal_pool — the journal twin of the
        scale WAL) between snapshots. ``wal_seq`` is the per-pool
        monotone the standby's keep-newest and ``apply_pool_wal`` dedupe
        on, so a replayed/duplicated delta collapses per scope.

        Ships a DELTA since the standby's last acked full entry when one
        exists; any gap (standby restarted, a frame lost, a need_full
        NACK) falls back to the full entry — correctness never depends
        on the delta chain, only the byte count does."""
        fo = self.failover
        if fo is None:
            return
        with self._lock:
            p = self._pools.get(name)
            if p is None:
                return
            p["wal_seq"] = int(p.get("wal_seq", 0)) + 1
            entry, ncut = self._truncate_wire(self._pool_wire(p))
            if ncut:
                self.wal_truncated += ncut
            base = self._wal_shipped.get(name)
        frame = entry if base is None else self._pool_delta(base, entry)
        ack = fo.wal_pool(name, frame)
        if ack is not None and ack.get("need_full") and frame is not entry:
            ack = fo.wal_pool(name, entry)
        with self._lock:
            if ack is not None and not ack.get("need_full"):
                self._wal_shipped[name] = entry
            else:
                # unacked: the standby's held base is unknown — next
                # mutation re-ships full and re-seeds the chain
                self._wal_shipped.pop(name, None)

    def apply_pool_wal(self, deltas: dict[str, Any],
                       keep_scope=None) -> int:
        """Adoption-time replay of per-pool WAL deltas (failover.py).
        Each delta carries the pool's full wire entry at mutation time
        (the standby merges delta frames on receive, so adoption never
        sees a frame); apply exactly those strictly newer (by wal_seq)
        than the adopted snapshot's copy — one pool's fresher journal
        never disturbs another's. ``keep_scope`` (scope-scoped adoption)
        filters to the scopes this host actually adopts. Returns the
        number of pools replayed."""
        n = 0
        with self._lock:
            for name, d in sorted(deltas.items()):
                entry = d.get("entry")
                if not entry or entry.get("delta"):
                    continue
                if keep_scope is not None \
                        and not keep_scope(pool_scope(name)):
                    continue
                cur = self._pools.get(name)
                if (cur is None or int(cur.get("wal_seq", 0))
                        < int(entry.get("wal_seq", 0))):
                    self._pools[name] = self._pool_from_wire(entry)
                    n += 1
        return n

    def scope_names(self) -> list[str]:
        """Every pool fence scope this manager holds state for (replica
        pools collapse into their group's scope) — the set a scoped
        adoption mints strictly-higher epochs for."""
        with self._lock:
            return sorted({pool_scope(n) for n in self._pools}
                          | {pool_scope(g) for g in self._groups})

    def to_wire(self) -> dict[str, Any]:
        with self._lock:
            return {
                "pools": {n: self._pool_wire(p)
                          for n, p in self._pools.items()},
                "jobs": {n: {"spec": dict(j["spec"]), "node": j["node"],
                             "stop_requested": bool(
                                 j.get("stop_requested")),
                             "status": dict(j["status"])
                             if j["status"] else None}
                         for n, j in self._jobs.items()},
                "groups": {n: self._group_wire_locked(g)
                           for n, g in self._groups.items()},
            }

    def load_wire(self, snap: dict[str, Any], keep_scope=None) -> None:
        """Adopt a replicated snapshot. ``keep_scope=None`` is the
        wholesale replace (the pre-ISSUE-15 standby shape). With a
        predicate, adoption is scope-scoped and MERGING: only pools/
        groups whose scope passes load, a local copy that is already
        NEWER (per-pool wal_seq / group next_seq — WAL replay may have
        landed first) is kept, and everything this manager already
        holds — a surviving owner's own scopes — stays untouched. Jobs
        always load: they are an acting-master duty, and a filtered
        load only ever runs while adopting mastership."""
        with self._lock:
            for n, p in snap.get("pools", {}).items():
                if keep_scope is not None \
                        and not keep_scope(pool_scope(n)):
                    continue
                cur = self._pools.get(n)
                if (keep_scope is not None and cur is not None
                        and int(cur.get("wal_seq", 0))
                        >= int(p.get("wal_seq", 0))):
                    continue
                self._pools[n] = self._pool_from_wire(p)
            for n, d in snap.get("groups", {}).items():
                if keep_scope is not None \
                        and not keep_scope(pool_scope(n)):
                    continue
                cur = self._groups.get(n)
                if (keep_scope is not None and cur is not None
                        and int(cur["next_seq"])
                        >= int(d.get("next_seq", 0))):
                    continue
                self._groups[n] = self._group_from_wire(d)
            self._jobs = {
                n: {"spec": dict(j["spec"]), "node": j["node"],
                    "stop_requested": bool(j.get("stop_requested")),
                    "status": dict(j["status"]) if j["status"] else None}
                for n, j in snap.get("jobs", {}).items()}
            if keep_scope is None:
                self._pools = {n: p for n, p in self._pools.items()
                               if n in snap.get("pools", {})}
                self._groups = {n: g for n, g in self._groups.items()
                                if n in snap.get("groups", {})}

    def on_adopt(self) -> None:
        """Called by the failover manager when this standby becomes the
        coordinator — per scope. A pool whose node is still ALIVE keeps
        its inflight node-id mappings and keeps serving uninterrupted:
        the per-pool WAL replicated its journal through the last terminal
        transition, the node-side idempotency key
        (``{name}:{rid}:{attempts}``) dedupes any re-forward, and the
        watchdog (``_requeue_stale_locked``) token-exactly replays the
        rare row whose drained completion the old master never
        replicated. So adopting one pool's fence costs the OTHER pools
        zero resubmission (the chaos cross-pool-isolation invariant).
        Pools/jobs on dead nodes are orphaned — inflight requeued with
        pinned seeds, exactly-once via the journal — and re-placed;
        both paths also retry from the pump."""
        alive = set(self.membership.members.alive_hosts())
        with self._lock:
            pool_names = []
            for name, pool in self._pools.items():
                if pool["node"] is not None and pool["node"] in alive:
                    continue            # scope keeps serving as-is
                self._orphan_pool_locked(name)
                pool_names.append(name)
            job_names = []
            for name, job in self._jobs.items():
                if (job["node"] is not None and job["node"] not in alive
                        and not self._job_over(job)):
                    job["node"] = None
                    job_names.append(name)
        # rebuilds + resubmissions go off-thread: adopt() is called on the
        # membership monitor loop, which must keep detecting failures (the
        # same discipline as _on_member_change); the pump retries whatever
        # fails here
        def _recover():
            for name in pool_names:
                self._recover_pool(name)
            for name in job_names:
                self._recover_job(name)

        threading.Thread(target=_recover, daemon=True,
                         name=f"{self.host}-lm-adopt").start()
