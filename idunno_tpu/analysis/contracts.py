"""Declared protocol contracts the checkers enforce.

These registries are the machine-readable half of CLAUDE.md's "when adding
a coordinator verb" rules. They are *declarations*, not detection: a new
mutating verb must be added to ``IDEM_VERBS`` (and its anchors must then
resolve), a new lock-guarded field to ``GUARDED``, a new retry site to
``RETRY_SAFE`` — the checkers fail loudly when an anchor no longer
resolves, so a refactor cannot silently shed a contract.
"""
from __future__ import annotations

import dataclasses

from idunno_tpu.analysis.core import Finding


@dataclasses.dataclass(frozen=True)
class Allow:
    """One reviewed suppression. ``symbol``/``tag`` may be ``"*"``; the
    justification is mandatory and must be a real sentence."""
    checker: str
    file: str
    symbol: str
    tag: str
    justification: str

    def __post_init__(self) -> None:
        if len(self.justification.strip()) < 20:
            raise ValueError(
                f"allowlist entry {self.checker}:{self.file}:{self.symbol}"
                f":{self.tag} needs a real justification sentence, got "
                f"{self.justification!r}")

    def matches(self, f: Finding) -> bool:
        return (self.checker == f.checker and self.file == f.file
                and self.symbol in ("*", f.symbol)
                and self.tag in ("*", f.tag))


@dataclasses.dataclass(frozen=True)
class IdemVerb:
    """A mutating verb and where its exactly-once story is anchored.

    kind="keyed": the client threads an idempotency key and the server
    dedupes it (anchor = the structure name that must appear in the
    anchored function). kind="natural": the verb is idempotent by
    construction (named resource / journaled deterministic counter); the
    anchor is the construct that makes it so."""
    verb: str
    kind: str                                  # "keyed" | "natural"
    anchors: tuple[tuple[str, str, str], ...]  # (file, qualname, marker)
    why: str = ""

    def __post_init__(self) -> None:
        if self.kind not in ("keyed", "natural"):
            raise ValueError(f"{self.verb}: kind {self.kind!r}")
        if self.kind == "natural" and len(self.why.strip()) < 20:
            raise ValueError(f"{self.verb}: a 'natural' idempotency claim "
                             "needs a justification sentence")


@dataclasses.dataclass(frozen=True)
class Guard:
    """Fields of ``cls`` in ``file`` that must only be touched under
    ``with self.<lock>``. Methods named ``*_locked`` assert the caller
    holds it (the repo's documented convention) and are exempt, as is
    ``__init__`` (no concurrency before construction completes)."""
    file: str
    cls: str
    lock: str
    fields: tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class RetrySite:
    """A ``call_with_retry`` call site and why retrying there is safe."""
    file: str
    symbol: str      # qualname of the enclosing function
    verbs: tuple[str, ...]   # idem-registry verbs it may carry
    why: str

    def __post_init__(self) -> None:
        if len(self.why.strip()) < 20:
            raise ValueError(f"retry site {self.file}:{self.symbol} needs "
                             "a justification sentence")


@dataclasses.dataclass(frozen=True)
class HedgeVerb:
    """An idempotent READ verb allowed to tail-hedge (ISSUE 20): fire a
    duplicate request at a second host and take the first reply. Only
    verbs declared here may appear at a ``call_hedged`` site — hedging a
    mutation would double-book exactly like an unkeyed retry."""
    verb: str
    why: str

    def __post_init__(self) -> None:
        if len(self.why.strip()) < 20:
            raise ValueError(f"hedge verb {self.verb!r} needs a "
                             "justification sentence")


@dataclasses.dataclass(frozen=True)
class HedgeSite:
    """A ``call_hedged`` call site and the read verbs it may carry."""
    file: str
    symbol: str      # qualname of the enclosing function
    verbs: tuple[str, ...]   # HEDGE_VERBS entries it may carry
    why: str

    def __post_init__(self) -> None:
        if len(self.why.strip()) < 20:
            raise ValueError(f"hedge site {self.file}:{self.symbol} needs "
                             "a justification sentence")


@dataclasses.dataclass
class Contracts:
    fence_targets: tuple[str, ...]
    stamp_targets: tuple[str, ...]
    determinism_targets: tuple[str, ...]
    idem_verbs: tuple[IdemVerb, ...]
    guarded: tuple[Guard, ...]
    retry_safe: tuple[RetrySite, ...]
    allowlist: tuple[Allow, ...]
    hedge_verbs: tuple[HedgeVerb, ...] = ()
    hedge_safe: tuple[HedgeSite, ...] = ()


# -- the shipped registries -------------------------------------------------

# modules whose ``transport.serve`` handlers must fence (everything — the
# checker itself exempts mutation-free handlers and membership gossip)
FENCE_TARGETS = ("idunno_tpu/",)

# modules whose transport send sites are coordinator-plane: every site
# must stamp an epoch, observe replies fence-aware, or be allowlisted
STAMP_TARGETS = ("idunno_tpu/serve/", "idunno_tpu/membership/",
                 "idunno_tpu/store/")

# chaos-reachable modules: no wall-clock/rng draws outside injection
DETERMINISM_TARGETS = ("idunno_tpu/serve/", "idunno_tpu/membership/",
                       "idunno_tpu/comm/", "idunno_tpu/store/",
                       "idunno_tpu/chaos.py")

IDEM_VERBS = (
    IdemVerb("submit", "keyed", anchors=(
        ("idunno_tpu/serve/inference_service.py",
         "InferenceService.submit_query", "idem"),
        ("idunno_tpu/serve/inference_service.py",
         "InferenceService._master_submit", "_idem"),
        # the key replicates with the failover snapshot, so a retry
        # against the ADOPTED master still dedupes
        ("idunno_tpu/serve/failover.py",
         "FailoverManager._snapshot_locked", "idem"),
    )),
    IdemVerb("lm_submit", "keyed", anchors=(
        # node-local dedupe of a manager's re-forward after a lost ACK
        ("idunno_tpu/serve/control.py",
         "ControlService._dispatch", "_lm_idem"),
        # manager-side: journaled key → rid map, replayed by recovery
        ("idunno_tpu/serve/lm_manager.py", "LMPoolManager.submit", "idem"),
    )),
    IdemVerb("put", "keyed", anchors=(
        ("idunno_tpu/store/sdfs.py", "FileStoreService.put_bytes", "idem"),
        ("idunno_tpu/store/sdfs.py", "FileStoreService._master_put",
         "_put_idem"),
    )),
    IdemVerb("train_start", "natural", anchors=(
        ("idunno_tpu/serve/control.py",
         "ControlService._dispatch", "already"),
        ("idunno_tpu/serve/lm_manager.py", "LMPoolManager.train",
         "already"),),
        why="train jobs are a named resource: a retried start finds the "
            "live job and is rejected/absorbed, never double-started"),
    IdemVerb("lm_serve", "natural", anchors=(
        ("idunno_tpu/serve/control.py",
         "ControlService._dispatch", "already"),),
        why="pools are a named resource: a duplicate serve returns "
            "already=True instead of building a second loop"),
    IdemVerb("group_scale", "natural", anchors=(
        # deterministic replica names off a journaled counter: a replayed
        # spawn decision resolves to the same "{group}@r{i}" and dedupes
        ("idunno_tpu/serve/lm_manager.py", "LMPoolManager.group_spawn",
         "next_replica"),),
        why="replica names derive from a journaled counter, so a replayed "
            "spawn decision recreates the same name instead of a twin"),
    IdemVerb("pool_wal", "natural", anchors=(
        # standby side keeps only the strictly newest per-pool entry
        ("idunno_tpu/serve/failover.py", "FailoverManager._handle",
         "pool_wal"),
        # delta frames merge only onto the exact acked base_seq; any gap
        # NACKs need_full and the sender re-ships the full entry
        ("idunno_tpu/serve/failover.py",
         "FailoverManager._merge_pool_delta_locked", "base_seq"),
        # adoption-time replay compares the per-pool monotone wal_seq
        ("idunno_tpu/serve/lm_manager.py", "LMPoolManager.apply_pool_wal",
         "wal_seq"),),
        why="per-pool WAL entries carry a monotone per-pool wal_seq; a "
            "duplicated or replayed delta collapses because receivers "
            "keep only strictly newer entries per pool scope, and a "
            "delta frame applies only on its exact acked base"),
    IdemVerb("pool_assign", "natural", anchors=(
        # the acting master hands a pool spec to its placed scope owner
        # by re-sending lm_serve with placement="assign"; the owner's
        # manager absorbs duplicates as a named resource
        ("idunno_tpu/serve/control.py",
         "ControlService._route_cluster", "assign"),
        ("idunno_tpu/serve/lm_manager.py", "LMPoolManager.serve",
         "already"),),
        why="pools are a named resource on the owner too: a replayed "
            "assign finds the live pool (or its _Starting reservation) "
            "and returns already=True instead of a second build"),
    IdemVerb("prefix_publish", "natural", anchors=(
        ("idunno_tpu/serve/control.py",
         "ControlService._dispatch", "prefix_publish"),
        # blobs are content-addressed by the rolling chunk hash: a
        # duplicate publish PUTs identical bytes under identical names
        ("idunno_tpu/serve/cluster_prefix.py",
         "ClusterPrefixCache.publish", "chain_names"),),
        why="chain blobs are content-addressed by the rolling token-chunk "
            "hash, so a duplicated or replayed publish writes the "
            "identical bytes under the identical SDFS names and the "
            "version history converges instead of forking"),
    IdemVerb("prefix_probe", "natural", anchors=(
        ("idunno_tpu/serve/control.py",
         "ControlService._dispatch", "prefix_probe"),
        ("idunno_tpu/serve/cluster_prefix.py",
         "ClusterPrefixCache.probe", "stat"),),
        why="probe is a pure read (ring STATs of content-addressed "
            "names); it mutates nothing on any node so a retried or "
            "duplicated probe is trivially exactly-once"),
    IdemVerb("prefix_fetch", "natural", anchors=(
        ("idunno_tpu/serve/control.py",
         "ControlService._dispatch", "prefix_fetch"),
        # grafting a chunk the radix tree already holds is a no-op: the
        # walk reuses the existing node instead of allocating a block
        ("idunno_tpu/serve/prefix_cache.py",
         "RadixPrefixCache.graft", "children"),),
        why="fetch grafts content-addressed chunks into the radix tree; "
            "chunks already present are reused not reallocated, so a "
            "duplicated fetch converges on the same tree and pool state"),
    IdemVerb("kv_handoff", "natural", anchors=(
        ("idunno_tpu/serve/control.py",
         "ControlService._dispatch", "kv_handoff"),
        # adopt decodes each KVC1 blob against the expected token chunk
        # (wrong-content blobs are refused, not grafted) and grafts via
        # the radix tree, which reuses chunks it already holds
        ("idunno_tpu/engine/serve_lm.py",
         "DecodeServer.handoff_adopt", "expect_tokens"),
        ("idunno_tpu/serve/prefix_cache.py",
         "RadixPrefixCache.graft", "children"),),
        why="a replayed ship re-probes the decode replica's radix depth "
            "and adopt grafts content-verified chunks that dedupe against "
            "blocks already held, so duplicated handoffs converge on the "
            "same block-pool state and the journaled request decodes once"),
)

GUARDED = (
    Guard("idunno_tpu/serve/control.py", "ControlService", "_reg_lock",
          ("_lm_loops", "_train_jobs", "_lm_idem")),
    Guard("idunno_tpu/serve/failover.py", "FailoverManager", "_lock",
          ("_seq", "_received", "_received_seq", "_wal", "_scale_wal",
           "_pool_wal", "_pool_wal_bytes")),
    Guard("idunno_tpu/membership/epoch.py", "ScopeOwners", "_lock",
          ("_map",)),
    Guard("idunno_tpu/membership/health.py", "HealthLedger", "_lock",
          ("_peers", "_remote")),
    Guard("idunno_tpu/serve/inference_service.py", "InferenceService",
          "_results_lock", ("_results", "_qnum", "_idem")),
    Guard("idunno_tpu/serve/inference_service.py", "InferenceService",
          "_jobs_lock", ("_jobs", "_pending_results")),
    Guard("idunno_tpu/serve/lm_manager.py", "LMPoolManager", "_lock",
          ("_pools", "_jobs", "_groups", "_wal_shipped")),
    Guard("idunno_tpu/store/sdfs.py", "FileStoreService", "_meta_lock",
          ("_put_idem", "_versions")),
)

RETRY_SAFE = (
    RetrySite("idunno_tpu/serve/inference_service.py",
              "InferenceService._master_call", verbs=("submit",),
              why="every mutating payload routed here carries the submit "
                  "idempotency key; reads are naturally idempotent"),
    RetrySite("idunno_tpu/store/sdfs.py", "FileStoreService._master_call",
              verbs=("put",),
              why="put carries the keyed idem; get/ls/stat are reads and "
                  "delete is a tombstone overwrite, idempotent by shape"),
    RetrySite("idunno_tpu/chaos.py", "ChaosCluster._client_control",
              verbs=("lm_submit", "train_start", "lm_serve"),
              why="harness client path mirrors real clients: mutating "
                  "verbs carry idem keys threaded by the workload"),
    RetrySite("idunno_tpu/serve/lm_manager.py",
              "LMPoolManager._handoff_ship", verbs=("kv_handoff",),
              why="kv_handoff is naturally idempotent: a replayed ship "
                  "re-probes the decode depth and adopt grafts dedupe "
                  "against blocks already held, converging on one state"),
)


# idempotent READ verbs that may tail-hedge (ISSUE 20). A new verb joins
# this table only with a sentence explaining why a duplicated, concurrent
# read converges — then a HEDGE_SAFE row names each call site.
HEDGE_VERBS = (
    HedgeVerb("lm_poll",
              why="poll delivery is at-most-once per completion and the "
                  "hedged caller merges the losing reply via on_late, so "
                  "a doubled poll neither loses nor double-delivers rows"),
    HedgeVerb("prefix_probe",
              why="probe is a pure read (ring STATs of content-addressed "
                  "names); it mutates nothing so concurrent duplicates "
                  "are trivially exactly-once"),
    HedgeVerb("sdfs_stat",
              why="STAT is a pure metadata read; masters max-merge "
                  "versions/tombstones so two replies can only disagree "
                  "transiently and the caller takes either"),
)

HEDGE_SAFE = (
    HedgeSite("idunno_tpu/store/sdfs.py", "FileStoreService.stat",
              verbs=("sdfs_stat", "prefix_probe"),
              why="stat hedges its pure STAT read across the master "
                  "chain; cluster_prefix probe/publish ride this same "
                  "read so prefix_probe is covered at the store layer"),
    HedgeSite("idunno_tpu/utils/lm_bench.py", "_gray_hedged_poll",
              verbs=("lm_poll",),
              why="the gray-suite client hedges lm_poll across two ring "
                  "hosts and merges the losing reply's completions via "
                  "on_late before counting delivered rows"),
)


def default() -> Contracts:
    from idunno_tpu.analysis.allowlist import ALLOWLIST
    return Contracts(
        fence_targets=FENCE_TARGETS,
        stamp_targets=STAMP_TARGETS,
        determinism_targets=DETERMINISM_TARGETS,
        idem_verbs=IDEM_VERBS,
        guarded=GUARDED,
        retry_safe=RETRY_SAFE,
        allowlist=tuple(ALLOWLIST),
        hedge_verbs=HEDGE_VERBS,
        hedge_safe=HEDGE_SAFE,
    )
