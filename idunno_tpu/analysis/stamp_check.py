"""stamp-check: coordinator send sites stamp epoch and trace together.

Contract (CLAUDE.md "when adding a coordinator verb"): a coordinator-
originated payload is stamped with the sender's fence view, and the trace
context rides beside the stamp. Mechanically, every transport send site
(``transport.call`` / ``transport.datagram`` / ``oneshot_call``) in the
coordinator-plane modules must satisfy one of:

- the enclosing function stamps an epoch: an ``"epoch"`` dict key /
  ``epoch=`` kwarg / ``payload["epoch"] = ...`` store, or a call to
  ``membership.epoch.stamp`` — the coordinator form;
- the enclosing function is fence-aware on the *reply* path: it calls
  ``reply_is_stale`` or ``observe_payload`` — the client form (clients
  never stamp; they learn the fence from whoever answers);
- an allowlist entry justifies the exception (e.g. read-only
  observability fan-out where replies carry no fence view).

Trace coupling: a function that opens a span (``spans.start``) AND sends
must also ``stamp_trace`` the payload — a span that never rides the wire
breaks the waterfall exactly where a request crosses hosts.
"""
from __future__ import annotations

import ast

from idunno_tpu.analysis.core import (Module, calls_named, checker, dotted,
                                      has_dict_key)

_SEND_ATTRS = ("transport.call", "transport.datagram")


def _send_calls(fn: ast.AST) -> list[tuple[ast.Call, str]]:
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name == "oneshot_call" or name.endswith(".oneshot_call"):
            out.append((node, "oneshot_call"))
        elif any(name == s or name.endswith("." + s)
                 for s in _SEND_ATTRS):
            out.append((node, name.split(".")[-1]))
    return out


@checker("stamp")
def check(modules: dict[str, Module], contracts) -> list:
    findings = []
    for rel, mod in modules.items():
        if not any(rel == t or rel.startswith(t)
                   for t in contracts.stamp_targets):
            continue
        seen_fns = set()
        for call, kind in _send_calls(mod.tree):
            fn = mod.enclosing_function(call)
            if fn is None or id(fn) in seen_fns:
                continue
            seen_fns.add(id(fn))
            stamps = (has_dict_key(fn, "epoch")
                      or bool(calls_named(fn, "stamp")))
            fence_aware = (bool(calls_named(fn, "reply_is_stale"))
                           or bool(calls_named(fn, "observe_payload")))
            if not stamps and not fence_aware:
                f = mod.finding(
                    "stamp", call, fn.name,
                    f"{kind} send in {fn.name!r} neither stamps an epoch "
                    f"(coordinator form) nor checks replies with "
                    f"reply_is_stale/observe_payload (client form) — a "
                    f"deposed sender would keep acting, a client would "
                    f"never learn the fence moved")
                if f is not None:
                    findings.append(f)
                    continue
            opens_span = any(
                dotted(c.func).endswith("spans.start")
                or dotted(c.func).endswith("self.spans.start")
                for c in ast.walk(fn) if isinstance(c, ast.Call))
            if opens_span and not calls_named(fn, "stamp_trace"):
                f = mod.finding(
                    "stamp", fn, f"{fn.name}:trace",
                    f"{fn.name!r} opens a span and sends, but never "
                    f"stamp_trace()s the payload — the trace breaks at "
                    f"the host boundary (stamp epoch and trace together)")
                if f is not None:
                    findings.append(f)
    return findings
