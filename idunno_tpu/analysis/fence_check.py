"""fence-check: every transport handler fences before it mutates.

Contract (CLAUDE.md, membership/epoch.py): a handler registered via
``transport.serve`` must run ``membership.epoch.check_payload`` before any
state mutation, so a deposed coordinator's stamped verbs are rejected
typed instead of corrupting adopted state. Exemptions, encoded here:

- membership gossip (modules under ``membership/``) calls
  ``observe_payload`` instead — gossip must carry ANY epoch so a deposed
  master learns it was deposed; rejecting stale gossip would prevent
  exactly that convergence.
- read-only handlers (no state mutation anywhere on their dispatch
  paths) have nothing to fence — e.g. the log-grep scanner.

Mutation = an assignment/del through ``self.<attr>`` (or a subscript of
one), or a call on a ``self.<attr>.<method>`` chain (conservatively: a
sub-object call may mutate it). Handlers that delegate (``return
self._x(msg)``) are analyzed through the delegate, three levels deep; a
delegate that fences internally before its own mutations counts as a
fence at the call site.
"""
from __future__ import annotations

import ast

from idunno_tpu.analysis.core import Module, checker, dotted


def _is_self_attr(node: ast.AST) -> bool:
    """self.<attr> or a subscript chain rooted at one."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def _events(mod: Module, cls: ast.ClassDef, fn: ast.FunctionDef,
            depth: int = 0):
    """Yield (lineno, kind) events in source order for ``fn``: kind in
    {"fence", "observe", "mutate"}. Delegate calls fold their callee's
    verdict into the call line."""
    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    events: list[tuple[int, str]] = []
    for node in ast.walk(fn):
        line = getattr(node, "lineno", 0)
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            if any(_is_self_attr(t) for t in targets):
                events.append((line, "mutate"))
        elif isinstance(node, ast.Delete):
            if any(_is_self_attr(t) for t in node.targets):
                events.append((line, "mutate"))
        elif isinstance(node, ast.Call):
            name = dotted(node.func)
            if name.endswith("check_payload"):
                events.append((line, "fence"))
            elif name.endswith("observe_payload"):
                events.append((line, "observe"))
            elif name.startswith("self."):
                parts = name.split(".")
                if len(parts) == 2 and parts[1] in methods:
                    if depth < 3:             # delegate: fold its verdict
                        sub = sorted(_events(mod, cls, methods[parts[1]],
                                             depth + 1))
                        verdict = _verdict(sub)
                        if verdict == "fenced":
                            events.append((line, "fence"))
                        elif verdict == "unfenced":
                            events.append((line, "mutate"))
                        if any(k == "observe" for _, k in sub):
                            events.append((line, "observe"))
                elif len(parts) >= 3:
                    # a call on a self-owned sub-object may mutate it
                    events.append((line, "mutate"))
    return events


def _verdict(events: list[tuple[int, str]]) -> str:
    """"clean" (no mutation), "fenced" (fence precedes first mutation,
    or fences and never mutates), or "unfenced"."""
    first_fence = min((ln for ln, k in events if k == "fence"),
                      default=None)
    first_mut = min((ln for ln, k in events if k == "mutate"),
                    default=None)
    if first_mut is None:
        return "fenced" if first_fence is not None else "clean"
    if first_fence is not None and first_fence <= first_mut:
        return "fenced"
    return "unfenced"


@checker("fence")
def check(modules: dict[str, Module], contracts) -> list:
    findings = []
    for rel, mod in modules.items():
        if not any(rel == t or rel.startswith(t)
                   for t in contracts.fence_targets):
            continue
        for call in (n for n in ast.walk(mod.tree)
                     if isinstance(n, ast.Call)):
            fname = dotted(call.func)
            if not fname.endswith("transport.serve") \
                    and fname != "transport.serve":
                continue
            if len(call.args) < 2:
                continue
            handler = call.args[1]
            cls = mod.enclosing_class(call)
            resolved = None
            if (cls is not None and isinstance(handler, ast.Attribute)
                    and isinstance(handler.value, ast.Name)
                    and handler.value.id == "self"):
                resolved = next(
                    (n for n in cls.body
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                     and n.name == handler.attr), None)
            if resolved is None:
                f = mod.finding(
                    "fence", call, f"handler:{dotted(handler) or '?'}",
                    "transport.serve handler is not a resolvable method "
                    "of this class — the fence contract cannot be "
                    "checked; register a named method")
                if f is not None:
                    findings.append(f)
                continue
            events = sorted(_events(mod, cls, resolved))
            verdict = _verdict(events)
            if verdict in ("clean", "fenced"):
                continue
            observes = any(k == "observe" for _, k in events)
            if observes and rel.startswith("idunno_tpu/membership/"):
                continue        # gossip exemption: observe, never reject
            first_mut = min(ln for ln, k in events if k == "mutate")
            f = mod.finding(
                "fence", resolved, resolved.name,
                f"handler {resolved.name!r} mutates state (first at line "
                f"{first_mut}) without a prior "
                f"membership.epoch.check_payload — a deposed "
                f"coordinator's stamped verbs would not be rejected")
            if f is not None:
                findings.append(f)
    return findings
