"""The reviewed suppression ledger — every entry carries its why.

One ``Allow`` row per call site (checker, file, symbol, tag). Adding a row
is a code-review decision, not a lint chore: the justification must say
why the contract does not apply HERE, and a row that stops matching
anything becomes a finding itself (core.run_analysis), so the ledger can
never drift from the tree. Zero unexplained entries ship (ISSUE 12).
"""
from idunno_tpu.analysis.contracts import Allow

ALLOWLIST = [
    # -- determinism-lint -------------------------------------------------
    Allow("determinism", "idunno_tpu/serve/control.py",
          "ControlService._dispatch", "secrets.randbits",
          "the generate verb without a caller seed explicitly promises "
          "varied samples per RPC; chaos workloads always pass seed=, so "
          "this draw is unreachable under a seeded schedule"),
    Allow("determinism", "idunno_tpu/serve/control.py",
          "ControlService._dispatch", "time.strftime",
          "names the profile-capture artifact directory after wall time; "
          "an observability filename, never journaled or replayed"),
    Allow("determinism", "idunno_tpu/serve/inference_service.py",
          "InferenceService.join_reassign_dispatch", "time.monotonic",
          "bounds the real-thread join wait for re-dispatch workers at "
          "shutdown/adoption; a pure watchdog deadline that never lands "
          "in journaled state (chaos drives a fake-thread engine)"),
    Allow("determinism", "idunno_tpu/store/sdfs.py",
          "FileStoreService.join_repair", "time.monotonic",
          "bounds the real-thread join wait for repair workers at "
          "shutdown; a pure watchdog deadline that never lands in "
          "journaled state (chaos drives repair synchronously)"),
    Allow("determinism", "idunno_tpu/chaos.py", "ChaosCluster.converge",
          "time.monotonic",
          "the harness's own convergence stopwatch: it MEASURES the real "
          "cluster from outside the simulation; faults and workload stay "
          "on the seeded rng/fake clock"),

    # -- fence-check ------------------------------------------------------
    Allow("fence", "idunno_tpu/serve/inference_service.py",
          "InferenceService._handle_result", "_handle_result",
          "worker results are valid at ANY epoch (membership/epoch.py): "
          "the handler observes the stamp — demoting us if the worker "
          "saw a higher fence — and the task book dedupes re-delivery; "
          "rejecting stale-stamped results would lose finished work"),

    # -- stamp-check ------------------------------------------------------
    Allow("stamp", "idunno_tpu/serve/control.py",
          "ControlService._dispatch", "_dispatch",
          "metrics_export relay: read-only observability fan-out on "
          "behalf of a client; the reply is a Prometheus text page with "
          "no fence view to observe and nothing a deposed sender could "
          "corrupt"),
    Allow("stamp", "idunno_tpu/serve/control.py",
          "ControlService._collect_trace", "_collect_trace",
          "trace assembly fans spans_dump to every member on behalf of a "
          "client; read-only, best-effort, and span buffers carry no "
          "fence state"),
]
