"""idem-check: the mutating-verb registry keeps its exactly-once anchors.

Contract (CLAUDE.md, comm/retry.py): every mutating verb carries a client
idempotency key deduped server-side (and replicated with the journal), or
is idempotent by construction (named resource / journaled deterministic
counter). ``contracts.IDEM_VERBS`` *declares* each verb and anchors the
code that implements its story: (file, qualname, marker). This checker
verifies the anchors still resolve — the anchored function exists and its
source still contains the marker — so a refactor that moves or drops a
dedupe path turns into a loud finding instead of a silent double-booking.

For ``kind="keyed"`` verbs it additionally requires a *use* of the key,
not just its mention: some anchored function must test membership or
``.get``/subscript the dedupe structure.
"""
from __future__ import annotations

from idunno_tpu.analysis.core import Finding, Module, checker


@checker("idem")
def check(modules: dict[str, Module], contracts) -> list:
    findings = []
    for verb in contracts.idem_verbs:
        key_used = False
        for file, qualname, marker in verb.anchors:
            mod = modules.get(file)
            if mod is None:
                findings.append(Finding(
                    "idem", file, 0, qualname, verb.verb,
                    f"idem registry anchor for verb {verb.verb!r} names a "
                    f"missing file — update contracts.IDEM_VERBS"))
                continue
            fn = mod.function(qualname)
            if fn is None:
                findings.append(Finding(
                    "idem", file, 0, qualname, verb.verb,
                    f"idem registry anchor for verb {verb.verb!r} names a "
                    f"missing function {qualname!r} — the dedupe moved; "
                    f"update contracts.IDEM_VERBS to its new home"))
                continue
            seg = mod.segment(fn)
            if marker not in seg:
                findings.append(Finding(
                    "idem", file, fn.lineno, qualname, verb.verb,
                    f"anchor {qualname!r} no longer mentions {marker!r} — "
                    f"the {verb.verb!r} exactly-once path may have been "
                    f"refactored away; re-anchor or restore it"))
                continue
            if verb.kind == "keyed" and (
                    f"in self.{marker}" in seg or f"in {marker}" in seg
                    or f"{marker}.get(" in seg or f"{marker}[" in seg):
                key_used = True
        if verb.kind == "keyed" and not key_used:
            f0, q0, m0 = verb.anchors[0]
            findings.append(Finding(
                "idem", f0, 0, q0, verb.verb,
                f"verb {verb.verb!r} is declared keyed but no anchor "
                f"actually *uses* its dedupe structure ({m0!r}: no "
                f"membership test / .get / subscript) — the key is "
                f"threaded but nothing dedupes it"))
    return findings
