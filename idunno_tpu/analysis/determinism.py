"""determinism-lint: no wall-clock or rng draws in chaos-reachable code.

The chaos harness (idunno_tpu/chaos.py) replays one seed through fake
clocks and a seeded network; a single ``time.time()`` read that lands in
journaled state, or one global-rng draw on a decision path, makes a
printed seed unreplayable. The contract (CLAUDE.md): chaos-reachable
modules draw time/randomness only through injected clock/seed parameters.

What counts as a draw (only *calls* are flagged — referencing
``time.monotonic`` to build a default parameter or pass an injection IS
the sanctioned mechanism and passes):

- ``time.time/monotonic/perf_counter/strftime/...`` calls
- ``datetime.now/utcnow/today`` calls (module or class form)
- module-level ``random.<draw>()`` calls, including via aliases and
  ``from random import ...``; ``random.Random(seed)`` with an argument is
  the injection idiom and passes, ``random.Random()`` bare does not
- any ``secrets.*`` call

``time.sleep`` is deliberately not flagged: pacing real threads is not a
clock *read* and never lands in journaled state. Draws on non-module
objects (``self.rng.random()``, ``self.clock()``) pass structurally —
that is the injected form. The ChaosCluster scripted-pressure rng rides
``self.rng`` and so needs no carve-out entry.
"""
from __future__ import annotations

import ast

from idunno_tpu.analysis.core import Module, checker

TIME_DRAWS = {"time", "monotonic", "perf_counter", "process_time",
              "thread_time", "time_ns", "monotonic_ns",
              "perf_counter_ns", "strftime", "localtime", "gmtime",
              "ctime", "asctime"}
DATETIME_DRAWS = {"now", "utcnow", "today"}
RANDOM_OK = {"Random", "SystemRandom", "seed", "getstate", "setstate"}


def _module_aliases(tree: ast.Module) -> dict[str, str]:
    """name -> stdlib module it binds ("time", "random", ...)."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("time", "random", "secrets", "datetime"):
                    out[a.asname or a.name] = a.name
    return out


def _from_imports(tree: ast.Module) -> dict[str, tuple[str, str]]:
    """bound name -> (module, original name) for the flagged modules."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
                "time", "random", "secrets", "datetime"):
            for a in node.names:
                out[a.asname or a.name] = (node.module, a.name)
    return out


def _names_in(expr: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


@checker("determinism")
def check(modules: dict[str, Module], contracts) -> list:
    findings = []
    for rel, mod in modules.items():
        if not any(rel == t or rel.startswith(t)
                   for t in contracts.determinism_targets):
            continue
        aliases = _module_aliases(mod.tree)
        froms = _from_imports(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            tag = _draw(node, aliases, froms)
            if tag is None:
                continue
            f = mod.finding(
                "determinism", node, tag,
                f"{tag}() draw in chaos-reachable module: route it "
                f"through an injected clock/rng parameter (see "
                f"comm/retry.py, serve/autoscaler.py for the idiom)")
            if f is not None:
                findings.append(f)
    return findings


def _draw(call: ast.Call, aliases: dict[str, str],
          froms: dict[str, tuple[str, str]]) -> str | None:
    """The dotted draw name if this call is a flagged draw, else None."""
    fn = call.func
    if isinstance(fn, ast.Name):
        src = froms.get(fn.id)
        if src is None:
            return None
        module, orig = src
        return _flagged(module, orig, call)
    if isinstance(fn, ast.Attribute):
        # receiver may be any expression mentioning a module alias
        # (``(rng or random).random`` still draws from the module)
        for name in _names_in(fn.value):
            module = aliases.get(name)
            if module is None and name in ("datetime", "date"):
                src = froms.get(name)
                module = src[0] if src else None
            if module is None:
                continue
            hit = _flagged(module, fn.attr, call)
            if hit:
                return hit
    return None


def _flagged(module: str, attr: str, call: ast.Call) -> str | None:
    if module == "time" and attr in TIME_DRAWS:
        return f"time.{attr}"
    if module == "datetime" and attr in DATETIME_DRAWS:
        return f"datetime.{attr}"
    if module == "secrets":
        return f"secrets.{attr}"
    if module == "random":
        if attr in RANDOM_OK:
            if attr == "Random" and not call.args and not call.keywords:
                return "random.Random"      # unseeded construction
            return None
        return f"random.{attr}"
    return None
