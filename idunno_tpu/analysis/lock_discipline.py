"""lock-discipline: documented lock-guarded fields stay under their lock.

Contract: fields declared in ``contracts.GUARDED`` (e.g.
``ControlService._reg_lock`` over ``_lm_loops``/``_train_jobs``) are only
read or written inside a ``with self.<lock>:`` block. Transports run one
handler thread per connection, so an unguarded registry read races the
guarded writes — a check-then-act on a torn view leaks a loop or double-
spawns a job.

Conventions honored:
- ``__init__`` is exempt (no concurrency before construction returns).
- methods named ``*_locked`` assert the caller holds the lock — the
  repo's documented convention — and are exempt; callers are checked at
  their own call sites instead.
- declaring a *different* registered lock in the ``with`` does NOT count:
  the field's declared lock is the one that serializes it.
"""
from __future__ import annotations

import ast

from idunno_tpu.analysis.core import Module, checker


def _with_locks(mod: Module, node: ast.AST) -> set[str]:
    """Names of self.<lock> contexts lexically enclosing ``node``."""
    out = set()
    for a in mod.ancestors(node):
        if isinstance(a, ast.With):
            for item in a.items:
                ctx = item.context_expr
                if (isinstance(ctx, ast.Attribute)
                        and isinstance(ctx.value, ast.Name)
                        and ctx.value.id == "self"):
                    out.add(ctx.attr)
    return out


@checker("lock")
def check(modules: dict[str, Module], contracts) -> list:
    findings = []
    for g in contracts.guarded:
        mod = modules.get(g.file)
        if mod is None:
            continue
        cls = mod.classes().get(g.cls)
        if cls is None:
            findings.append(mod.finding(
                "lock", mod.tree, g.cls,
                f"GUARDED registry names class {g.cls!r} which no longer "
                f"exists in {g.file} — update contracts.GUARDED")
                or _never())
            continue
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in g.fields):
                continue
            fn = mod.enclosing_function(node)
            if fn is None or fn.name == "__init__" \
                    or fn.name.endswith("_locked"):
                continue
            if g.lock in _with_locks(mod, node):
                continue
            f = mod.finding(
                "lock", node, f"{node.attr}@{fn.name}",
                f"{g.cls}.{node.attr} accessed in {fn.name!r} outside "
                f"'with self.{g.lock}:' — handler threads race the "
                f"guarded writers (declared in contracts.GUARDED)")
            if f is not None:
                findings.append(f)
    # one finding per (symbol, tag) — a field read twice in one method is
    # one discipline violation, not two ledger entries
    seen, out = set(), []
    for f in findings:
        k = (f.file, f.symbol, f.tag)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def _never():
    raise AssertionError("class-level findings are never pragma-suppressed")
