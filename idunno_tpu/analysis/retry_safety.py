"""retry-safety: retries only where exactly-once is guaranteed.

Three rules, grounded in comm/retry.py and membership/epoch.py:

1. every ``call_with_retry`` call site must be declared in
   ``contracts.RETRY_SAFE`` (file + enclosing qualname + the idem-registry
   verbs it may carry, with a justification). An undeclared site is a
   finding: retrying an unkeyed mutation double-books on a lost ACK.
   Declared sites are cross-checked — every verb they claim must exist in
   the idem registry, and a declared site that no longer exists is stale.

2. ``StaleEpoch`` is never caught-and-retried: an ``except`` clause that
   names StaleEpoch and then calls a send/retry helper (or ``continue``s
   a loop that does) is a finding — a fenced coordinator must step down,
   not hammer the new owner. Catching it to *stop* (log, return, raise)
   is the sanctioned shape.

3. nobody forges the fence: constructing ``TransportError(...,
   reason="stale_epoch")`` outside membership/epoch.py would bypass the
   typed never-retryable contract.
"""
from __future__ import annotations

import ast

from idunno_tpu.analysis.core import Finding, Module, checker, dotted


def _handles_stale(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    names = []
    if t is not None:
        names = [dotted(n) for n in ast.walk(t)
                 if isinstance(n, (ast.Name, ast.Attribute))]
    return any(n.endswith("StaleEpoch") for n in names)


@checker("retry")
def check(modules: dict[str, Module], contracts) -> list:
    findings = []
    declared = {(s.file, s.symbol): s for s in contracts.retry_safe}
    seen_sites = set()
    idem_verbs = {v.verb for v in contracts.idem_verbs}

    for s in contracts.retry_safe:
        for v in s.verbs:
            if v not in idem_verbs:
                findings.append(Finding(
                    "retry", s.file, 0, s.symbol, f"verb:{v}",
                    f"RETRY_SAFE site {s.symbol!r} claims verb {v!r} "
                    f"which is not in the idem registry — declare the "
                    f"verb's exactly-once story first"))

    for rel, mod in modules.items():
        if not rel.startswith("idunno_tpu/"):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and (
                    dotted(node.func).endswith("call_with_retry")):
                qual = mod.qualname(node)
                seen_sites.add((rel, qual))
                if (rel, qual) not in declared:
                    f = mod.finding(
                        "retry", node, qual,
                        f"call_with_retry in {qual!r} is not declared in "
                        f"contracts.RETRY_SAFE — an unkeyed mutating verb "
                        f"retried after a lost ACK double-books; declare "
                        f"the site with the verbs it carries and why "
                        f"each is retry-safe")
                    if f is not None:
                        findings.append(f)
            elif isinstance(node, ast.ExceptHandler) \
                    and _handles_stale(node):
                resends = any(
                    isinstance(c, ast.Call) and any(
                        dotted(c.func).endswith(x) for x in
                        ("call_with_retry", "transport.call",
                         "oneshot_call", ".datagram"))
                    for c in ast.walk(node))
                loops_on = any(isinstance(c, ast.Continue)
                               for c in ast.walk(node))
                if resends or loops_on:
                    f = mod.finding(
                        "retry", node, mod.qualname(node),
                        "except StaleEpoch handler retries/continues — a "
                        "fenced coordinator must step down (the typed "
                        "rejection is never retryable by design)")
                    if f is not None:
                        findings.append(f)
            elif isinstance(node, ast.Call) \
                    and dotted(node.func).endswith("TransportError") \
                    and rel != "idunno_tpu/membership/epoch.py":
                if any(kw.arg == "reason"
                       and isinstance(kw.value, ast.Constant)
                       and kw.value.value == "stale_epoch"
                       for kw in node.keywords):
                    f = mod.finding(
                        "retry", node, mod.qualname(node),
                        "TransportError(reason='stale_epoch') forged "
                        "outside membership/epoch.py — raise the typed "
                        "StaleEpoch so retry/step-down semantics hold")
                    if f is not None:
                        findings.append(f)

    for (file, symbol), s in declared.items():
        if (file, symbol) not in seen_sites:
            findings.append(Finding(
                "retry", file, 0, symbol, "stale-site",
                f"RETRY_SAFE declares {symbol!r} in {file} but no "
                f"call_with_retry site exists there anymore — remove or "
                f"re-anchor the declaration"))
    return findings


@checker("hedge")
def check_hedge(modules: dict[str, Module], contracts) -> list:
    """hedge-safety: tail-hedged duplicates only for declared read verbs.

    Mirrors the retry checker for ``call_hedged`` (ISSUE 20): every call
    site must be declared in ``contracts.HEDGE_SAFE``, every verb a site
    claims must exist in the ``HEDGE_VERBS`` registry of idempotent
    reads, and stale declarations are findings. Hedging an undeclared
    verb is the same bug class as retrying an unkeyed mutation — the
    duplicate request lands twice."""
    findings = []
    hedge_sites = tuple(getattr(contracts, "hedge_safe", ()) or ())
    hedge_verbs = {v.verb for v in
                   getattr(contracts, "hedge_verbs", ()) or ()}
    declared = {(s.file, s.symbol): s for s in hedge_sites}
    seen_sites = set()

    for s in hedge_sites:
        for v in s.verbs:
            if v not in hedge_verbs:
                findings.append(Finding(
                    "hedge", s.file, 0, s.symbol, f"verb:{v}",
                    f"HEDGE_SAFE site {s.symbol!r} claims verb {v!r} "
                    f"which is not in HEDGE_VERBS — declare why a "
                    f"duplicated concurrent read of it converges first"))

    for rel, mod in modules.items():
        if not rel.startswith("idunno_tpu/"):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and (
                    dotted(node.func).endswith("call_hedged")):
                qual = mod.qualname(node)
                seen_sites.add((rel, qual))
                if (rel, qual) not in declared:
                    f = mod.finding(
                        "hedge", node, qual,
                        f"call_hedged in {qual!r} is not declared in "
                        f"contracts.HEDGE_SAFE — a hedged mutation lands "
                        f"twice; declare the site with its idempotent "
                        f"read verbs and why first-reply-wins is safe")
                    if f is not None:
                        findings.append(f)

    for (file, symbol), s in declared.items():
        if (file, symbol) not in seen_sites:
            findings.append(Finding(
                "hedge", file, 0, symbol, "stale-site",
                f"HEDGE_SAFE declares {symbol!r} in {file} but no "
                f"call_hedged site exists there anymore — remove or "
                f"re-anchor the declaration"))
    return findings
