"""Shared machinery for the protocol-contract checkers.

One ``Module`` per source file: the parsed AST plus the derived maps every
checker needs (parent links, qualnames, per-line ``# lint:`` pragmas).
Checkers register themselves in ``CHECKERS`` via the ``checker`` decorator
and receive the full module map — each filters down to its own targets, so
one parse pass serves all six.

Suppression is two-tier, both requiring a justification:
- ``analysis/allowlist.py`` entries (checker, file, symbol, tag) — the
  reviewed ledger; unmatched entries are themselves findings so the
  ledger can never rot.
- an inline ``# lint: ok <checker> -- <why>`` pragma on the flagged line,
  for cases where the justification belongs next to the code.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re


@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str
    file: str        # repo-relative posix path
    line: int
    symbol: str      # qualname of the enclosing def/class ("" = module)
    tag: str         # stable, line-independent token for allowlisting
    message: str

    @property
    def key(self) -> str:
        return f"{self.checker}:{self.file}:{self.symbol}:{self.tag}"

    def to_wire(self) -> dict:
        return {"checker": self.checker, "file": self.file,
                "line": self.line, "symbol": self.symbol,
                "tag": self.tag, "message": self.message}


_PRAGMA = re.compile(r"#\s*lint:\s*ok\s+([\w,-]+)\s*--\s*(\S.*)")


class Module:
    """A parsed source file with the derived maps checkers share."""

    def __init__(self, path: str, relpath: str) -> None:
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=path)
        self.parents: dict[ast.AST, ast.AST] = {}
        self._quals: dict[ast.AST, str] = {}
        self._index(self.tree, None, "")
        # line -> set of checker names granted by an inline pragma (a
        # pragma without a justification after ``--`` never parses, so
        # every suppression carries its why)
        self.pragmas: dict[int, set[str]] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _PRAGMA.search(text)
            if m:
                self.pragmas[i] = set(m.group(1).split(","))

    def _index(self, node: ast.AST, parent: ast.AST | None,
               qual: str) -> None:
        if parent is not None:
            self.parents[node] = parent
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            qual = f"{qual}.{node.name}" if qual else node.name
            self._quals[node] = qual
        for child in ast.iter_child_nodes(node):
            self._index(child, node, qual)

    # -- lookups -----------------------------------------------------------

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing(self, node: ast.AST, kinds) -> ast.AST | None:
        for a in self.ancestors(node):
            if isinstance(a, kinds):
                return a
        return None

    def enclosing_function(self, node: ast.AST):
        return self.enclosing(node, (ast.FunctionDef, ast.AsyncFunctionDef))

    def enclosing_class(self, node: ast.AST):
        return self.enclosing(node, ast.ClassDef)

    def qualname(self, node: ast.AST) -> str:
        scope = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef,
                   ast.ClassDef)) else self.enclosing(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
        return self._quals.get(scope, "") if scope is not None else ""

    def classes(self) -> dict[str, ast.ClassDef]:
        return {n.name: n for n in self.tree.body
                if isinstance(n, ast.ClassDef)}

    def function(self, qualname: str):
        """Resolve a dotted qualname ("Class.method" or "fn") to its def."""
        for node, q in self._quals.items():
            if q == qualname and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return node
        return None

    def segment(self, node: ast.AST) -> str:
        return ast.get_source_segment(self.source, node) or ""

    def suppressed(self, checker: str, line: int) -> bool:
        names = self.pragmas.get(line)
        return names is not None and (checker in names or "all" in names)

    def finding(self, checker: str, node: ast.AST, tag: str,
                message: str) -> Finding | None:
        line = getattr(node, "lineno", 0)
        if self.suppressed(checker, line):
            return None
        return Finding(checker, self.relpath, line,
                       self.qualname(node), tag, message)


# -- helpers used by several checkers ---------------------------------------

def dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ("self.transport.call")."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return ""


def call_name(call: ast.Call) -> str:
    return dotted(call.func)


def has_dict_key(fn: ast.AST, key: str) -> bool:
    """True if any dict literal / subscript-store / kwarg inside ``fn``
    carries ``key`` — the shape every wire-stamp takes."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and k.value == key:
                    return True
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == key:
                    return True
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.slice, ast.Constant)
                        and t.slice.value == key):
                    return True
    return False


def calls_in(fn: ast.AST) -> list[ast.Call]:
    return [n for n in ast.walk(fn) if isinstance(n, ast.Call)]


def calls_named(fn: ast.AST, suffix: str) -> list[ast.Call]:
    """Calls whose dotted name ends with ``suffix`` (``check_payload``
    matches both the bare import and ``epoch.check_payload``)."""
    out = []
    for c in calls_in(fn):
        name = call_name(c)
        if name == suffix or name.endswith("." + suffix):
            out.append(c)
    return out


# -- registry + runner ------------------------------------------------------

CHECKERS: dict[str, object] = {}


def checker(name: str):
    def wrap(fn):
        CHECKERS[name] = fn
        fn.checker_name = name
        return fn
    return wrap


def load_modules(root: str,
                 subdirs=("idunno_tpu",)) -> dict[str, Module]:
    """Parse every .py under ``root``'s subdirs into Modules, keyed by
    repo-relative posix path. Unparseable files raise — a tree that does
    not parse has bigger problems than protocol drift."""
    modules: dict[str, Module] = {}
    for sub in subdirs:
        base = os.path.join(root, sub)
        if os.path.isfile(base):
            rel = os.path.relpath(base, root)
            modules[rel.replace(os.sep, "/")] = Module(base, rel)
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root)
                modules[rel.replace(os.sep, "/")] = Module(path, rel)
    return modules


def run_analysis(root: str, contracts=None, checkers=None,
                 modules: dict[str, Module] | None = None) -> dict:
    """Run the registered checkers and apply the allowlist.

    Returns {"findings": [Finding...], "files_scanned": int,
             "allowlisted": int, "allowlist_size": int,
             "by_checker": {name: count}} — findings include one
    ``allowlist`` entry per allowlist row that matched nothing (a stale
    suppression is a finding too: the ledger must describe the tree)."""
    # import here, not at module top: contracts imports checkers' registries
    from idunno_tpu.analysis import contracts as contracts_mod
    from idunno_tpu.analysis import (determinism, fence_check,  # noqa: F401
                                     idem_check, lock_discipline,
                                     retry_safety, stamp_check)
    ctr = contracts if contracts is not None else contracts_mod.default()
    if modules is None:
        modules = load_modules(root)
    names = list(checkers) if checkers else sorted(CHECKERS)
    raw: list[Finding] = []
    for name in names:
        raw.extend(CHECKERS[name](modules, ctr))
    kept: list[Finding] = []
    used = [False] * len(ctr.allowlist)
    suppressed = 0
    for f in raw:
        hit = False
        for i, a in enumerate(ctr.allowlist):
            if a.matches(f):
                used[i] = True
                hit = True
        if hit:
            suppressed += 1
        else:
            kept.append(f)
    for i, a in enumerate(ctr.allowlist):
        # an entry can only be judged stale by the checker that owns it:
        # a subset run (e.g. the chaos-soak determinism preflight) must
        # not age entries belonging to checkers that did not run
        if a.checker not in names:
            continue
        if not used[i]:
            kept.append(Finding(
                "allowlist", a.file, 0, a.symbol, a.tag,
                f"allowlist entry matches nothing on the tree "
                f"(checker={a.checker!r}): remove it or fix its anchor"))
    kept.sort(key=lambda f: (f.file, f.line, f.checker))
    by: dict[str, int] = {}
    for f in kept:
        by[f.checker] = by.get(f.checker, 0) + 1
    return {"findings": kept, "files_scanned": len(modules),
            "allowlisted": suppressed,
            "allowlist_size": len(ctr.allowlist), "by_checker": by}
