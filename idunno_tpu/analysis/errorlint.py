"""Built-in error-class linter: the ruff.toml baseline without ruff.

The container image does not ship ruff (and nothing may be pip-installed),
so the pinned error-class baseline (``ruff.toml``: F / E9 / PLE — classes
that are outright bugs, never style) is enforceable offline by this
fallback. ``tests/test_error_baseline.py`` prefers real ruff when a binary
is on PATH and falls back here otherwise; both must read ZERO on the tree.

Implemented checks (a deliberate, high-precision subset):

- E999  syntax error (``compile()`` — also catches tab/indent errors)
- F401  unused import (module scope; ``__init__.py`` skipped — re-export
        surface; names in ``__all__`` count as used)
- F841  local variable assigned but never read (simple ``name = ...``
        targets only; ``_``-prefixed names exempt by convention)
- F632  ``is``/``is not`` comparison against a str/number literal
- F541  f-string without any placeholder
- F821  undefined name — LENIENT: one module-wide defined-name set (no
        scope modeling), annotation subtrees skipped, wildcard imports
        disable it for the file; only true typos survive the filter

A ``# noqa`` comment on the flagged line suppresses it (bare, or listing
the code). Output mirrors the checkers' Finding shape so the two lint
surfaces read alike.
"""
from __future__ import annotations

import ast
import builtins
import os
import re
import warnings

_NOQA = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z0-9, ]+))?", re.I)


def _noqa_lines(source: str) -> dict[int, set[str] | None]:
    """line -> set of codes (None = bare noqa, suppress everything)."""
    out: dict[int, set[str] | None] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _NOQA.search(text)
        if m:
            codes = m.group("codes")
            out[i] = (None if not codes else
                      {c.strip().upper() for c in codes.split(",")})
    return out


class _FileLint:
    def __init__(self, path: str, relpath: str) -> None:
        self.relpath = relpath
        with open(path, encoding="utf-8") as f:
            self.source = f.read()
        self.noqa = _noqa_lines(self.source)
        self.problems: list[dict] = []

    def flag(self, code: str, line: int, message: str) -> None:
        codes = self.noqa.get(line, ())
        if codes is None or (codes and code in codes):
            return
        self.problems.append({"code": code, "file": self.relpath,
                              "line": line, "message": message})

    def run(self) -> list[dict]:
        try:
            tree = ast.parse(self.source, filename=self.relpath)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", SyntaxWarning)
                compile(self.source, self.relpath, "exec")
        except SyntaxError as e:
            # E999 is never noqa-suppressible: a tree that does not parse
            # cannot be trusted to have parsed its own noqa comment
            self.problems.append({
                "code": "E999", "file": self.relpath,
                "line": e.lineno or 0, "message": f"syntax error: {e.msg}"})
            return self.problems
        except ValueError as e:   # e.g. null bytes
            self.problems.append({"code": "E999", "file": self.relpath,
                                  "line": 0, "message": str(e)})
            return self.problems
        self._f401(tree)
        self._f541_f632(tree)
        self._f841(tree)
        self._f821(tree)
        return self.problems

    # -- F401: unused module-scope imports --------------------------------

    def _f401(self, tree: ast.Module) -> None:
        if os.path.basename(self.relpath) == "__init__.py":
            return                         # re-export surface
        bound: dict[str, tuple[int, str]] = {}
        for node in tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    bound[name] = (node.lineno, a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        return             # can't reason about usage
                    bound[a.asname or a.name] = (node.lineno, a.name)
        if not bound:
            return
        used: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                base = node
                while isinstance(base, ast.Attribute):
                    base = base.value
                if isinstance(base, ast.Name):
                    used.add(base.id)
            elif (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                used.add(node.value)       # __all__ strings, doc refs
        for name, (line, orig) in bound.items():
            if name not in used:
                self.flag("F401", line, f"{orig!r} imported but unused")

    # -- F541 / F632 ------------------------------------------------------

    def _f541_f632(self, tree: ast.Module) -> None:
        # a "{x:08x}" format spec is itself a JoinedStr of constants on
        # py<3.12 — those are never F541
        specs = {id(n.format_spec) for n in ast.walk(tree)
                 if isinstance(n, ast.FormattedValue)
                 and n.format_spec is not None}
        for node in ast.walk(tree):
            if isinstance(node, ast.JoinedStr) and id(node) not in specs:
                if not any(isinstance(v, ast.FormattedValue)
                           for v in node.values):
                    self.flag("F541", node.lineno,
                              "f-string without any placeholders")
            elif isinstance(node, ast.Compare):
                for op, cmp in zip(node.ops, node.comparators):
                    if isinstance(op, (ast.Is, ast.IsNot)):
                        for side in (node.left, cmp):
                            if (isinstance(side, ast.Constant)
                                    and isinstance(side.value, (str, int,
                                                                float))
                                    and not isinstance(side.value, bool)):
                                self.flag("F632", node.lineno,
                                          "use == to compare str/num "
                                          "literals, not 'is'")

    # -- F841: assigned-but-never-read locals -----------------------------

    def _f841(self, tree: ast.Module) -> None:
        for fn in (n for n in ast.walk(tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))):
            reads: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                             ast.Load):
                    reads.add(node.id)
                elif isinstance(node, (ast.Global, ast.Nonlocal)):
                    reads.update(node.names)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if (isinstance(t, ast.Name)
                            and not t.id.startswith("_")
                            and t.id not in reads):
                        self.flag("F841", node.lineno,
                                  f"local {t.id!r} assigned but never "
                                  f"used")

    # -- F821: lenient undefined-name -------------------------------------

    def _f821(self, tree: ast.Module) -> None:
        defined = set(dir(builtins)) | {"__file__", "__name__", "__doc__",
                                        "__package__", "__spec__",
                                        "__builtins__", "__debug__"}
        for node in ast.walk(tree):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                defined.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                if not isinstance(node, ast.Lambda):
                    defined.add(node.name)
                a = node.args
                for arg in (a.posonlyargs + a.args + a.kwonlyargs
                            + ([a.vararg] if a.vararg else [])
                            + ([a.kwarg] if a.kwarg else [])):
                    defined.add(arg.arg)
            elif isinstance(node, ast.ClassDef):
                defined.add(node.name)
            elif isinstance(node, ast.Import):
                for al in node.names:
                    defined.add(al.asname or al.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for al in node.names:
                    if al.name == "*":
                        return             # wildcard: give up, stay quiet
                    defined.add(al.asname or al.name)
            elif isinstance(node, ast.ExceptHandler) and node.name:
                defined.add(node.name)
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                defined.update(node.names)
        skip: set[int] = set()             # ids of annotation subtrees
        for node in ast.walk(tree):
            ann = getattr(node, "annotation", None)
            if ann is not None:
                for sub in ast.walk(ann):
                    skip.add(id(sub))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.returns is not None:
                for sub in ast.walk(node.returns):
                    skip.add(id(sub))
        for node in ast.walk(tree):
            if (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and id(node) not in skip
                    and node.id not in defined):
                self.flag("F821", node.lineno,
                          f"undefined name {node.id!r}")


def lint_paths(root: str, targets) -> list[dict]:
    """Lint every .py under the given files/dirs (repo-relative)."""
    problems: list[dict] = []
    for target in targets:
        base = os.path.join(root, target)
        if os.path.isfile(base):
            problems.extend(_FileLint(base, target).run())
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                problems.extend(_FileLint(path, rel).run())
    problems.sort(key=lambda p: (p["file"], p["line"], p["code"]))
    return problems


# the baseline surface: the package, the drivers, the tools — tests are
# exercised by pytest itself and excluded on purpose (fixture files seed
# deliberate violations)
BASELINE_TARGETS = ("idunno_tpu", "tools", "bench.py", "__graft_entry__.py")


def main() -> int:
    import json
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    problems = lint_paths(root, BASELINE_TARGETS)
    print(json.dumps({"suite": "errorlint", "problems_total": len(problems),
                      "problems": problems[:50]}))
    return 0 if not problems else 1


if __name__ == "__main__":
    raise SystemExit(main())
