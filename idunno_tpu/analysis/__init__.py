"""Protocol-contract static analyzer (ISSUE 12).

The control plane's safety rests on conventions the chaos harness can only
certify *after* the fact (a forgotten ``check_payload`` in a new verb shows
up as a split-brain seed, if a schedule happens to hit it). This package
enforces the conventions mechanically, at the AST level, before any soak:

- fence-check      — every ``transport.serve`` handler fences with
                     ``membership.epoch.check_payload`` before mutating
                     (membership gossip observes instead, by design)
- stamp-check      — coordinator-originated send sites stamp epoch and
                     trace together (or are fence-aware clients)
- idem-check       — the declared mutating-verb registry keeps its client
                     key + server dedupe anchors through refactors
- determinism-lint — no wall-clock/rng draws in chaos-reachable modules
                     outside the injected clock/seed parameters
- lock-discipline  — fields documented as lock-guarded are only touched
                     under ``with`` on that lock
- retry-safety     — ``call_with_retry`` only wraps registered-safe verbs;
                     ``StaleEpoch`` is never caught-and-retried

Driver: ``python tools/protocol_lint.py`` (ONE JSON line, like bench.py).
Gate: ``tests/test_protocol_lint.py`` asserts zero findings on the tree;
``tools/chaos_soak.py`` refuses to soak over determinism-lint findings.

Suppressions go in ``analysis/allowlist.py`` — one entry per call site,
each with a mandatory justification sentence.
"""
from idunno_tpu.analysis.core import (CHECKERS, Finding, Module,
                                      load_modules, run_analysis)

__all__ = ["CHECKERS", "Finding", "Module", "load_modules", "run_analysis"]
