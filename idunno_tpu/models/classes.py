"""ImageNet category names.

The reference downloads ``imagenet_classes.txt`` from the pytorch hub repo at
call time (`alexnet_resnet.py:29-38`) and maps top-1 indices to names
(`:41-42, 87`). We load the same file if it exists locally (search path:
$IDUNNO_IMAGENET_CLASSES, ./imagenet_classes.txt), else fall back to synthetic
``class_<idx>`` names — zero-egress environments must still classify.
"""
from __future__ import annotations

import functools
import os


@functools.lru_cache(maxsize=1)
def imagenet_categories() -> tuple[str, ...]:
    for path in (os.environ.get("IDUNNO_IMAGENET_CLASSES"),
                 "imagenet_classes.txt"):
        if path and os.path.exists(path):
            with open(path) as f:
                names = tuple(s.strip() for s in f if s.strip())
            if len(names) >= 1000:
                return names[:1000]
    return tuple(f"class_{i}" for i in range(1000))
