"""Transformer with pluggable attention — the long-context model family.

The reference serves only image CNNs (`alexnet_resnet.py`), but the
framework's job inventory must cover sequence models at TPU scale: this
module provides a causal/bidirectional transformer whose attention
implementation is injectable — ``full_attention`` on one device, or
``ring_attention`` with the sequence dimension sharded over the mesh
(`idunno_tpu.parallel.ring_attention`) for contexts that do not fit one
chip. Rotary position embeddings keep positions global and length-agnostic,
and they are applied on the (sequence-sharded) global view under jit, so
each shard rotates with its true global positions.
"""
from __future__ import annotations

from collections.abc import Callable
from functools import partial

import flax.linen as nn
import jax.numpy as jnp
import jax

from idunno_tpu.parallel.ring_attention import full_attention

AttnFn = Callable[..., jnp.ndarray]     # (q, k, v, *, causal) -> out
# (dim, dtype, param_dtype, name) -> flax module replacing the dense MLP
FfnFactory = Callable[..., nn.Module]


def rope(x: jnp.ndarray, *, base: float = 10000.0) -> jnp.ndarray:
    """Rotary embedding over [B, T, H, D] with global positions 0..T-1."""
    b, t, h, d = x.shape
    half = d // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = jnp.arange(t, dtype=jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, :, None, :]      # [1, T, 1, half]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


class MultiHeadAttention(nn.Module):
    dim: int
    num_heads: int
    causal: bool = True
    attn_fn: AttnFn = full_attention
    use_rope: bool = True
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, t, _ = x.shape
        head_dim = self.dim // self.num_heads
        dense = partial(nn.DenseGeneral, dtype=self.dtype,
                        param_dtype=self.param_dtype)
        q = dense(features=(self.num_heads, head_dim), name="q")(x)
        k = dense(features=(self.num_heads, head_dim), name="k")(x)
        v = dense(features=(self.num_heads, head_dim), name="v")(x)
        if self.use_rope:
            q, k = rope(q), rope(k)
        out = self.attn_fn(q, k, v, causal=self.causal)
        return nn.DenseGeneral(features=self.dim, axis=(-2, -1),
                               dtype=self.dtype,
                               param_dtype=self.param_dtype,
                               name="out")(out)


class Block(nn.Module):
    """Pre-LN block with pluggable attention AND pluggable FFN — MoE and
    other conditional-compute families swap the MLP via ``ffn_factory``
    instead of duplicating the residual wiring."""

    dim: int
    num_heads: int
    mlp_ratio: int = 4
    causal: bool = True
    attn_fn: AttnFn = full_attention
    ffn_factory: FfnFactory | None = None
    use_rope: bool = True
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        ln = partial(nn.LayerNorm, dtype=self.dtype,
                     param_dtype=self.param_dtype)
        x = x + MultiHeadAttention(
            self.dim, self.num_heads, causal=self.causal,
            attn_fn=self.attn_fn, use_rope=self.use_rope, dtype=self.dtype,
            param_dtype=self.param_dtype, name="attn")(ln(name="ln1")(x))
        h_in = ln(name="ln2")(x)
        if self.ffn_factory is not None:
            return x + self.ffn_factory(
                dim=self.dim, dtype=self.dtype,
                param_dtype=self.param_dtype, name="ffn")(h_in)
        dense = partial(nn.Dense, dtype=self.dtype,
                        param_dtype=self.param_dtype)
        h = dense(self.dim * self.mlp_ratio, name="mlp_up")(h_in)
        return x + dense(self.dim, name="mlp_down")(nn.gelu(h))


class TransformerLM(nn.Module):
    """Minimal causal LM for long-context serving/training demos.

    ``ffn_factory`` swaps the dense MLP for another FFN (e.g. a switch-MoE
    layer) on every ``ffn_every``-th block (counting from the last block
    backwards, the Switch-Transformer interleaving); the remaining blocks
    keep the dense MLP.
    """

    vocab: int = 1024
    dim: int = 128
    depth: int = 2
    num_heads: int = 4
    causal: bool = True
    attn_fn: AttnFn = full_attention
    ffn_factory: FfnFactory | None = None
    ffn_every: int = 1
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        if self.ffn_every < 1:
            raise ValueError(f"ffn_every={self.ffn_every}: must be >= 1")
        x = nn.Embed(self.vocab, self.dim, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="embed")(tokens)
        for i in range(self.depth):
            use_ffn = (self.ffn_factory is not None
                       and (self.depth - 1 - i) % self.ffn_every == 0)
            x = Block(self.dim, self.num_heads, causal=self.causal,
                      attn_fn=self.attn_fn,
                      ffn_factory=self.ffn_factory if use_ffn else None,
                      dtype=self.dtype,
                      param_dtype=self.param_dtype, name=f"block{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                         name="ln_f")(x)
        logits = nn.Dense(self.vocab, dtype=self.dtype,
                          param_dtype=self.param_dtype, name="head")(x)
        return logits.astype(jnp.float32)
