"""Transformer with pluggable attention — the long-context model family.

The reference serves only image CNNs (`alexnet_resnet.py`), but the
framework's job inventory must cover sequence models at TPU scale: this
module provides a causal/bidirectional transformer whose attention
implementation is injectable — ``full_attention`` on one device, or
``ring_attention`` with the sequence dimension sharded over the mesh
(`idunno_tpu.parallel.ring_attention`) for contexts that do not fit one
chip. Rotary position embeddings keep positions global and length-agnostic,
and they are applied on the (sequence-sharded) global view under jit, so
each shard rotates with its true global positions.
"""
from __future__ import annotations

from collections.abc import Callable
from functools import partial

import flax.linen as nn
import jax.numpy as jnp
import jax

from idunno_tpu.ops.paged_attention import (merge_attention,
                                            paged_attention_grouped)
from idunno_tpu.parallel.ring_attention import full_attention

AttnFn = Callable[..., jnp.ndarray]     # (q, k, v, *, causal) -> out
# (dim, dtype, param_dtype, name) -> flax module replacing the dense MLP
FfnFactory = Callable[..., nn.Module]


def make_attn_fn(kind: str = "auto", *, mesh=None, axis: str = "data",
                 **kw) -> AttnFn:
    """One knob for the attention kernel family:

      full    — reference XLA attention (single device)
      flash   — Pallas blockwise kernel, training-capable (custom VJP);
                pass ``interpret=True`` off-TPU
      ring    — blockwise ring attention, sequence sharded over ``mesh``
      ulysses — all-to-all head re-sharding over ``mesh``
      auto    — flash on TPU, full elsewhere

    ring/ulysses require ``mesh`` (the sequence axis is ``axis``)."""
    from functools import partial as _p

    if mesh is not None and kind not in ("ring", "ulysses"):
        # a mesh means sequence parallelism, which only ring/ulysses do —
        # silently dropping it would serve single-device attention
        raise ValueError(f"attn kind {kind!r} ignores mesh; "
                         "use kind='ring' or 'ulysses'")
    auto = kind == "auto"
    if auto:
        import jax as _jax
        kind = "flash" if _jax.devices()[0].platform == "tpu" else "full"
    if kind == "full":
        # auto may resolve here holding flash-only kwargs — drop them (the
        # graceful-degradation path); an EXPLICIT 'full' with kwargs is a
        # caller error and must not be silently ignored
        if kw and not auto:
            raise TypeError(f"full attention takes no kwargs, got {kw}")
        return full_attention
    if kind == "flash":
        from idunno_tpu.ops.flash_attention import flash_attention
        return _p(flash_attention, **kw) if kw else flash_attention
    if kind in ("ring", "ulysses"):
        if mesh is None:
            raise ValueError(f"attn kind {kind!r} needs a mesh")
        if kind == "ring":
            from idunno_tpu.parallel.ring_attention import ring_attention
            return _p(ring_attention, mesh=mesh, seq_axis=axis, **kw)
        from idunno_tpu.parallel.ulysses import ulysses_attention
        return _p(ulysses_attention, mesh=mesh, seq_axis=axis, **kw)
    raise ValueError(f"unknown attention kind {kind!r}; "
                     "want auto|full|flash|ring|ulysses")


def rope(x: jnp.ndarray, *, base: float = 10000.0,
         positions: jnp.ndarray | None = None) -> jnp.ndarray:
    """Rotary embedding over [B, T, H, D]; ``positions`` overrides the
    default global positions 0..T-1 — shape [T] (shared across the batch;
    decode steps pass their absolute position so cached keys and the new
    query rotate consistently) or [B, T] (per-row positions, the
    continuous-batching decode where every row sits at its own depth)."""
    b, t, h, d = x.shape
    half = d // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    if positions is None:
        positions = jnp.arange(t, dtype=jnp.float32)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., half]
    if angles.ndim == 2:                         # [T, half] → [1, T, 1, half]
        angles = angles[None]
    cos = jnp.cos(angles)[:, :, None, :]         # [1|B, T, 1, half]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


class MultiHeadAttention(nn.Module):
    """Pluggable-kernel attention; ``decode=True`` switches to single-token
    autoregressive serving with a KV cache in the flax "cache" collection
    (zero-init via `init`, threaded through `apply(..., mutable=["cache"])`
    by `idunno_tpu.engine.generate`).

    ``num_kv_heads`` < num_heads is grouped-query attention (GQA): groups
    of query heads share one K/V head, shrinking the decode KV cache —
    the dominant HBM tenant of long-context serving — by the group factor
    while the MXU compute shape is unchanged. num_kv_heads == num_heads
    (default) is exact MHA; num_kv_heads == 1 is MQA."""

    dim: int
    num_heads: int
    num_kv_heads: int | None = None
    causal: bool = True
    attn_fn: AttnFn = full_attention
    use_rope: bool = True
    decode: bool = False
    max_decode_len: int = 0
    decode_per_row: bool = False
    # "native" stores K/V at the compute dtype; "int8" stores symmetric
    # per-(row, position, head) int8 with float32 scales — ~4x (vs f32) /
    # ~2x (vs bf16) less KV-cache HBM, the long-context serving lever
    # alongside GQA. Lossy: greedy streams can drift from the native-cache
    # model's (opt-in; the exactness oracles run on "native").
    kv_cache_dtype: str = "native"
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @property
    def _kv_heads(self) -> int:
        kv = (self.num_heads if self.num_kv_heads is None
              else self.num_kv_heads)
        if kv < 1:
            raise ValueError(f"num_kv_heads {kv} must be >= 1 "
                             "(1 = MQA; None = MHA)")
        if self.num_heads % kv:
            raise ValueError(f"num_heads {self.num_heads} must be a "
                             f"multiple of num_kv_heads {kv}")
        return kv

    @nn.compact
    def __call__(self, x, paged=None):
        b, t, _ = x.shape
        head_dim = self.dim // self.num_heads
        kv_heads = self._kv_heads
        dense = partial(nn.DenseGeneral, dtype=self.dtype,
                        param_dtype=self.param_dtype)
        q = dense(features=(self.num_heads, head_dim), name="q")(x)
        k = dense(features=(kv_heads, head_dim), name="k")(x)
        v = dense(features=(kv_heads, head_dim), name="v")(x)
        if self.decode:
            return self._decode_step(q, k, v, paged=paged)
        if paged is not None:
            raise ValueError("paged KV attention is a decode-mode feature")
        if self.use_rope:
            q, k = rope(q), rope(k)
        if kv_heads != self.num_heads:
            # the training/prefill forward repeats K/V up to the query
            # heads so every attn_fn (full/flash/ring/ulysses) runs
            # unchanged — the GQA saving is the CACHE, which only the
            # decode path holds
            rep = self.num_heads // kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        out = self.attn_fn(q, k, v, causal=self.causal)
        return nn.DenseGeneral(features=self.dim, axis=(-2, -1),
                               dtype=self.dtype,
                               param_dtype=self.param_dtype,
                               name="out")(out)

    def _decode_step(self, q, k, v, paged=None):
        """Autoregressive serving against the KV cache — three shapes:

        scalar cursor, t=1: one token in, one out (``engine.generate``);
        scalar cursor, t>1: CHUNKED prefill — the whole prompt in one apply,
          K/V written at cursor..cursor+t-1, causal within the chunk;
        per-row cursors (``decode_per_row``): continuous batching — every
          batch row sits at its own depth, cursors are int32 [B] and OWNED
          BY THE CALLER (read, never advanced here; the serving loop
          advances only its live rows — `engine.serve_lm.DecodeServer`);
          t>1 is the per-row chunk: row r writes K/V at cursors[r]..
          cursors[r]+t-1, causal within the chunk (speculative-decoding
          verification feeds the whole draft in one apply).

        Uses its own cached softmax-attention kernel — any correct causal
        ``attn_fn`` (full/ring/flash) is numerically equivalent, so the
        training-time kernel choice does not matter here; non-causal models
        cannot be decoded autoregressively and are rejected.

        ``paged`` (an `ops.paged_attention.PagedContext`) splits the key
        space: cache positions [paged.start, paged.start + lengths[r])
        of row r are EXCLUDED from the slot-local mask and served from
        the block pool THROUGH the block table instead (no contiguous
        gather); the two normalized partials merge exactly via their
        log-sum-exps (`merge_attention`). A row's own chunk positions
        always sit beyond its paged region, so the local partial is
        never empty; zero-length chains contribute weight exactly 0."""
        if self.max_decode_len <= 0:
            raise ValueError("decode=True needs max_decode_len > 0")
        if not self.causal:
            raise ValueError("decode=True requires causal=True "
                             "(autoregressive serving of a bidirectional "
                             "model would silently change its semantics)")
        if self.kv_cache_dtype not in ("native", "int8"):
            raise ValueError(f"kv_cache_dtype {self.kv_cache_dtype!r}: "
                             "want native|int8")
        quant = self.kv_cache_dtype == "int8"
        b, t, h, d = q.shape
        kv_heads = k.shape[2]          # < h under GQA: the cache saving
        ck = self.variable("cache", "cached_k", jnp.zeros,
                           (b, self.max_decode_len, kv_heads, d),
                           jnp.int8 if quant else k.dtype)
        cv = self.variable("cache", "cached_v", jnp.zeros,
                           (b, self.max_decode_len, kv_heads, d),
                           jnp.int8 if quant else v.dtype)
        ks = vs = None
        if quant:
            ks = self.variable("cache", "k_scale", jnp.zeros,
                               (b, self.max_decode_len, kv_heads),
                               jnp.float32)
            vs = self.variable("cache", "v_scale", jnp.zeros,
                               (b, self.max_decode_len, kv_heads),
                               jnp.float32)

        def q8(x):
            """Symmetric int8 over the head dim: [.., kv_heads, d] →
            (int8 values, float32 scale [.., kv_heads])."""
            xf = x.astype(jnp.float32)
            s = jnp.maximum(jnp.abs(xf).max(axis=-1) / 127.0, 1e-8)
            vals = jnp.clip(jnp.round(xf / s[..., None]), -127, 127)
            return vals.astype(jnp.int8), s
        if self.decode_per_row:
            cur = self.variable("cache", "cursors",
                                lambda: jnp.zeros((b,), jnp.int32))
            i = cur.value                                  # [B]
            # per-row positions [B, t]: row r covers i[r]..i[r]+t-1
            pos_bt = i[:, None] + jnp.arange(t)[None, :]
            # overflow guard: keep the cache intact and poison the scores
            # to NaN so misuse is loud, not silent
            overflow = i + t > self.max_decode_len         # [B]
            if self.use_rope:
                p = pos_bt.astype(jnp.float32)
                q, k = rope(q, positions=p), rope(k, positions=p)
            slot = jnp.clip(pos_bt, 0, self.max_decode_len - 1)  # [B, t]
            rows = jnp.arange(b)
            if quant:
                (k_st, k_sc), (v_st, v_sc) = q8(k), q8(v)
            else:
                k_st, v_st = k, v
            # overflow gating happens on the VALUES before the scatter (an
            # overflowing row re-writes its old cache entries — a no-op),
            # never as a post-scatter jnp.where over the whole cache: that
            # select would keep the pre-scatter cache live, forcing XLA to
            # COPY the full [B, L, H, D] buffer every layer every decode
            # step instead of scattering in place (the dominant cost of
            # the 2026-07-31 capture's 6.8 ms decode step)
            ovr_g = overflow[:, None, None, None]            # [B,1,1,1]
            old_k = ck.value[rows[:, None], slot]            # [B,t,kv,d]
            old_v = cv.value[rows[:, None], slot]
            new_k = ck.value.at[rows[:, None], slot].set(
                jnp.where(ovr_g, old_k, k_st))
            new_v = cv.value.at[rows[:, None], slot].set(
                jnp.where(ovr_g, old_v, v_st))
            new_ks = new_vs = None
            if quant:
                ovr_s = overflow[:, None, None]
                new_ks = ks.value.at[rows[:, None], slot].set(
                    jnp.where(ovr_s, ks.value[rows[:, None], slot], k_sc))
                new_vs = vs.value.at[rows[:, None], slot].set(
                    jnp.where(ovr_s, vs.value[rows[:, None], slot], v_sc))
            if not self.is_initializing():  # init returns a CLEAN cache;
                ck.value, cv.value = new_k, new_v   # cursors: caller-owned
                if quant:
                    ks.value, vs.value = new_ks, new_vs
            # [B, 1, t, T]: row r's chunk position j attends slots ≤ i[r]+j
            ax = jnp.arange(self.max_decode_len)[None, None, :]
            live = ax <= pos_bt[:, :, None]
            if paged is not None:
                # the paged interval is served through the block table —
                # exclude it here so the merge never double-counts keys
                live &= ~((ax >= paged.start)
                          & (ax < paged.start + paged.lengths[:, None, None]))
            mask = live[:, None, :, :]
            poison = overflow[:, None, None, None, None]
        else:
            cur = self.variable("cache", "cursor",
                                lambda: jnp.zeros((), jnp.int32))
            i = cur.value
            pos = (i + jnp.arange(t)).astype(jnp.float32)  # [T]
            overflow = i + t > self.max_decode_len
            if self.use_rope:
                q, k = rope(q, positions=pos), rope(k, positions=pos)
            if quant:
                (k_st, k_sc), (v_st, v_sc) = q8(k), q8(v)
            else:
                k_st, v_st = k, v
            # same value-gating as the per-row branch: on overflow the
            # update writes back the OLD slice (dynamic_slice/-update
            # clamp the start identically, so the round-trip is a no-op)
            # instead of post-selecting over the whole cache, which would
            # block the in-place update and copy the full buffer
            old_k = jax.lax.dynamic_slice(ck.value, (0, i, 0, 0),
                                          k_st.shape)
            old_v = jax.lax.dynamic_slice(cv.value, (0, i, 0, 0),
                                          v_st.shape)
            new_k = jax.lax.dynamic_update_slice(
                ck.value, jnp.where(overflow, old_k, k_st), (0, i, 0, 0))
            new_v = jax.lax.dynamic_update_slice(
                cv.value, jnp.where(overflow, old_v, v_st), (0, i, 0, 0))
            new_ks = new_vs = None
            if quant:
                old_ks = jax.lax.dynamic_slice(ks.value, (0, i, 0),
                                               k_sc.shape)
                old_vs = jax.lax.dynamic_slice(vs.value, (0, i, 0),
                                               v_sc.shape)
                new_ks = jax.lax.dynamic_update_slice(
                    ks.value, jnp.where(overflow, old_ks, k_sc), (0, i, 0))
                new_vs = jax.lax.dynamic_update_slice(
                    vs.value, jnp.where(overflow, old_vs, v_sc), (0, i, 0))
            if not self.is_initializing():  # init must return a CLEAN cache
                ck.value, cv.value, cur.value = new_k, new_v, i + t
                if quant:
                    ks.value, vs.value = new_ks, new_vs
            # [q, T]: chunk position j attends cache slots ≤ i + j
            ax = jnp.arange(self.max_decode_len)[None, :]
            live = ax <= (i + jnp.arange(t))[:, None]
            if paged is not None:
                # batch-1 in the scalar-cursor shape: one chain length
                live &= ~((ax >= paged.start)
                          & (ax < paged.start + paged.lengths[0]))
            mask = live[None, None, :, :]
            poison = overflow
        # grouped attention against the (possibly narrower) cache: query
        # heads reshape to [.., kv_heads, group, d] so the einsum reads
        # the small cache straight from HBM — no repeat materialization.
        # group == 1 is exact MHA (identical contraction).
        group = h // kv_heads
        if quant:
            new_k = new_k.astype(jnp.float32) * new_ks[..., None]
            new_v = new_v.astype(jnp.float32) * new_vs[..., None]
        q5 = q.reshape(b, t, kv_heads, group, d)
        # f32 casts on the operands: they FUSE into the dot reads (HBM
        # traffic stays at the cache's stored width), and XLA:CPU's
        # emulated-bf16 dots make a native-dtype einsum measurably slower
        # in the test/dev loop — measured 2026-07-31, 103→116 ms/step
        scores = jnp.einsum("bqhgd,bthd->bhgqt", q5.astype(jnp.float32),
                            new_k.astype(jnp.float32)) / (d ** 0.5)
        mask = mask[:, :, None]          # broadcast over the group axis
        scores = jnp.where(poison, jnp.nan, scores)
        scores = jnp.where(mask, scores, -jnp.inf)
        if paged is None:
            weights = jax.nn.softmax(scores, axis=-1)
            out = jnp.einsum("bhgqt,bthd->bqhgd", weights,
                             new_v.astype(jnp.float32)).astype(self.dtype)
        else:
            # explicit softmax so the local partial exposes its lse for
            # the exact merge with the paged partial; the query's own
            # chunk positions are always live locally, so m_l is finite
            # (NaN poison still propagates — overflow stays loud)
            m_l = jnp.max(scores, axis=-1, keepdims=True)
            p_l = jnp.exp(scores - jax.lax.stop_gradient(m_l))
            l_l = jnp.sum(p_l, axis=-1, keepdims=True)
            # normalize BEFORE the value einsum — the exact op order of
            # jax.nn.softmax + einsum above, so a row whose paged chain
            # is empty reproduces the dense branch bit-for-bit
            o_l = jnp.einsum("bhgqt,bthd->bqhgd", p_l / l_l,
                             new_v.astype(jnp.float32))
            lse_l = jnp.transpose((m_l + jnp.log(l_l))[..., 0],
                                  (0, 3, 1, 2))           # [b, t, kvh, g]
            o_p, lse_p = paged_attention_grouped(
                q5.astype(jnp.float32), paged.k_pages, paged.v_pages,
                paged.tables, paged.lengths,
                k_scale_pages=paged.k_scale_pages,
                v_scale_pages=paged.v_scale_pages,
                kernel=paged.kernel, interpret=paged.interpret)
            out = merge_attention(o_l, lse_l, o_p, lse_p).astype(self.dtype)
        out = out.reshape(b, t, h, d)
        return nn.DenseGeneral(features=self.dim, axis=(-2, -1),
                               dtype=self.dtype,
                               param_dtype=self.param_dtype,
                               name="out")(out)


class Block(nn.Module):
    """Pre-LN block with pluggable attention AND pluggable FFN — MoE and
    other conditional-compute families swap the MLP via ``ffn_factory``
    instead of duplicating the residual wiring."""

    dim: int
    num_heads: int
    num_kv_heads: int | None = None
    mlp_ratio: int = 4
    causal: bool = True
    attn_fn: AttnFn = full_attention
    ffn_factory: FfnFactory | None = None
    use_rope: bool = True
    decode: bool = False
    max_decode_len: int = 0
    decode_per_row: bool = False
    kv_cache_dtype: str = "native"
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, paged=None):
        ln = partial(nn.LayerNorm, dtype=self.dtype,
                     param_dtype=self.param_dtype)
        x = x + MultiHeadAttention(
            self.dim, self.num_heads, num_kv_heads=self.num_kv_heads,
            causal=self.causal,
            attn_fn=self.attn_fn, use_rope=self.use_rope,
            decode=self.decode, max_decode_len=self.max_decode_len,
            decode_per_row=self.decode_per_row,
            kv_cache_dtype=self.kv_cache_dtype,
            dtype=self.dtype,
            param_dtype=self.param_dtype, name="attn")(
                ln(name="ln1")(x), paged=paged)
        h_in = ln(name="ln2")(x)
        if self.ffn_factory is not None:
            return x + self.ffn_factory(
                dim=self.dim, dtype=self.dtype,
                param_dtype=self.param_dtype, name="ffn")(h_in)
        dense = partial(nn.Dense, dtype=self.dtype,
                        param_dtype=self.param_dtype)
        h = dense(self.dim * self.mlp_ratio, name="mlp_up")(h_in)
        return x + dense(self.dim, name="mlp_down")(nn.gelu(h))


class TransformerLM(nn.Module):
    """Minimal causal LM for long-context serving/training demos.

    ``ffn_factory`` swaps the dense MLP for another FFN (e.g. a switch-MoE
    layer) on every ``ffn_every``-th block (counting from the last block
    backwards, the Switch-Transformer interleaving); the remaining blocks
    keep the dense MLP.
    """

    vocab: int = 1024
    dim: int = 128
    depth: int = 2
    num_heads: int = 4
    num_kv_heads: int | None = None   # < num_heads = GQA; None = MHA
    causal: bool = True
    attn_fn: AttnFn = full_attention
    ffn_factory: FfnFactory | None = None
    ffn_every: int = 1
    decode: bool = False
    max_decode_len: int = 0
    decode_per_row: bool = False
    # "int8": quantized KV cache in decode mode (see MultiHeadAttention)
    kv_cache_dtype: str = "native"
    remat: bool = False
    # scan_layers=True marks the SCANNED decode twin: params/cache leaves
    # carry a leading depth axis and the layer loop is one `lax.scan`
    # (`scanned_apply`). The flax module itself must never run in this
    # mode — `decode_apply` is the only entry point; the unscanned module
    # stays the canonical layout for init/checkpointing/training.
    scan_layers: bool = False
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        if self.scan_layers:
            raise ValueError(
                "scan_layers=True models hold depth-stacked params/cache "
                "and cannot run through the flax per-layer loop; call "
                "decode_apply (models.transformer) instead of .apply")
        if self.ffn_every < 1:
            raise ValueError(f"ffn_every={self.ffn_every}: must be >= 1")
        # remat: recompute each block's activations in the backward pass
        # instead of storing them — activation memory drops from O(depth·T·d)
        # to O(T·d) at ~1/3 extra FLOPs, the standard long-context trade
        block_cls = nn.remat(Block) if self.remat else Block
        x = nn.Embed(self.vocab, self.dim, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="embed")(tokens)
        for i in range(self.depth):
            use_ffn = (self.ffn_factory is not None
                       and (self.depth - 1 - i) % self.ffn_every == 0)
            x = block_cls(self.dim, self.num_heads,
                          num_kv_heads=self.num_kv_heads,
                          causal=self.causal,
                          attn_fn=self.attn_fn,
                          ffn_factory=self.ffn_factory if use_ffn else None,
                          decode=self.decode,
                          max_decode_len=self.max_decode_len,
                          decode_per_row=self.decode_per_row,
                          kv_cache_dtype=self.kv_cache_dtype,
                          dtype=self.dtype,
                          param_dtype=self.param_dtype, name=f"block{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                         name="ln_f")(x)
        logits = nn.Dense(self.vocab, dtype=self.dtype,
                          param_dtype=self.param_dtype, name="head")(x)
        return logits.astype(jnp.float32)


# -- scanned decode: the layer loop as ONE lax.scan -------------------------
#
# The per-layer Python loop above emits `depth` separate fusion groups per
# decode step; at serving dims each group is a handful of small ops, so the
# step time is dominated by dispatch overhead rather than the HBM-bound
# weight stream (TRACE_LM_DECODE.json: 1.98 ms measured vs ~1.03 ms bound).
# Stacking every block's params/cache on a leading depth axis and scanning
# `Block.apply` collapses the loop to one fused scan body. The scan body IS
# `Block.apply` on one layer's slice — same module, same math, same order —
# but XLA's scan-body fusion may move float rounding by ~1 ULP vs the
# unrolled loop, so exactness is enforced STRUCTURALLY instead: serving and
# `engine.generate` run the IDENTICAL scanned step, and every oracle test
# (tests/test_serve_lm.py) pins the streams against each other.


def scan_compatible(model: TransformerLM) -> bool:
    """Whether a model's blocks are homogeneous enough to scan: every
    block must run the same program on its own param/cache slice, which a
    per-block ``ffn_factory`` (MoE interleaving) breaks — those models
    keep the per-layer loop."""
    return model.ffn_factory is None


def stack_block_params(params, depth: int):
    """Per-block params → the scanned layout: ``block0..block{L-1}``
    subtrees are stacked leaf-wise onto a leading depth axis under
    ``"blocks"``; embed/ln_f/head pass through. Works on quantized trees
    too (QTensor is a pytree — q and scale stack independently, and
    `ops.quantize.dequantize_tree`'s per-leaf broadcast is rank-agnostic,
    so quantize-then-stack preserves the dequantized numerics)."""
    blocks = [params[f"block{i}"] for i in range(depth)]
    return {
        "embed": params["embed"],
        "blocks": jax.tree.map(lambda *xs: jnp.stack(xs), *blocks),
        "ln_f": params["ln_f"],
        "head": params["head"],
    }


def scanned_apply(model: TransformerLM, params, cache, tokens, paged=None):
    """One decode/prefill step of a ``scan_layers=True`` model: embed →
    `lax.scan` of `Block.apply` over the depth-stacked (params, cache) →
    final norm → logits. Returns ``(float32 logits, new cache)`` — the
    same contract as ``model.apply(..., mutable=["cache"])`` unpacked,
    with the cache's leading axis the layer index.

    ``paged`` carries depth-stacked page stores (``[L, N, bs, ...]``,
    `engine.kv_blocks.KVBlockPool.kv_pages`); the scan slices each
    layer's page array alongside its params/cache slice, so the block
    pool is read in place — never gathered."""
    blk = Block(model.dim, model.num_heads,
                num_kv_heads=model.num_kv_heads,
                causal=model.causal,
                attn_fn=model.attn_fn,
                ffn_factory=None,
                decode=model.decode,
                max_decode_len=model.max_decode_len,
                decode_per_row=model.decode_per_row,
                kv_cache_dtype=model.kv_cache_dtype,
                dtype=model.dtype,
                param_dtype=model.param_dtype)
    x = nn.Embed(model.vocab, model.dim, dtype=model.dtype,
                 param_dtype=model.param_dtype).apply(
        {"params": params["embed"]}, tokens)

    if paged is None:
        def body(h, layer):
            p_l, c_l = layer
            h, mut = blk.apply({"params": p_l, "cache": c_l}, h,
                               mutable=["cache"])
            return h, mut["cache"]

        x, new_cache = jax.lax.scan(body, x, (params["blocks"], cache))
    else:
        pages = (paged.k_pages, paged.v_pages,
                 paged.k_scale_pages, paged.v_scale_pages)

        def body(h, layer):
            p_l, c_l, (kp, vp, ksp, vsp) = layer
            h, mut = blk.apply({"params": p_l, "cache": c_l}, h,
                               paged=paged.layer(kp, vp, ksp, vsp),
                               mutable=["cache"])
            return h, mut["cache"]

        x, new_cache = jax.lax.scan(
            body, x, (params["blocks"], cache, pages))
    x = nn.LayerNorm(dtype=model.dtype, param_dtype=model.param_dtype
                     ).apply({"params": params["ln_f"]}, x)
    logits = nn.Dense(model.vocab, dtype=model.dtype,
                      param_dtype=model.param_dtype).apply(
        {"params": params["head"]}, x)
    return logits.astype(jnp.float32), new_cache


def decode_apply(model: TransformerLM, params, cache, tokens, paged=None):
    """THE decode-step entry point: dispatches on ``model.scan_layers``
    so callers (`engine.serve_lm`, `engine.generate`) are layout-blind.
    Returns ``(float32 logits, new cache)``."""
    if getattr(model, "scan_layers", False):
        return scanned_apply(model, params, cache, tokens, paged=paged)
    if paged is not None:
        raise ValueError(
            "paged KV attention requires the scanned decode layout "
            "(scan_layers=True): page stores are depth-stacked")
    logits, mut = model.apply({"params": params, "cache": cache}, tokens,
                              mutable=["cache"])
    return logits, mut["cache"]
