"""Vision Transformer — a third servable image-classification family.

The reference serves exactly two torchvision CNNs
(`alexnet_resnet.py:17-22`); the registry here is extensible
(`idunno_tpu.models.register_model`) and ViT demonstrates that the serving
engine, scheduler, and shell are model-agnostic: ViT drops into
`InferenceEngine` through the same ``(images, train=False) → logits``
contract as AlexNet/ResNet, and is an even better MXU fit (its FLOPs are
plain batched matmuls).

Reuses the pre-LN `idunno_tpu.models.transformer.Block` (bidirectional, no
RoPE — learned position embeddings, the standard ViT recipe), so kernel
improvements (e.g. the Pallas flash attention ``attn_fn``) apply to the
vision family automatically.
"""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from idunno_tpu.models.transformer import AttnFn, Block
from idunno_tpu.parallel.ring_attention import full_attention


class ViT(nn.Module):
    """ViT-/16 style classifier over NHWC uint8-preprocessed images."""

    num_classes: int = 1000
    patch: int = 16
    dim: int = 384            # ViT-S defaults
    depth: int = 12
    num_heads: int = 6
    attn_fn: AttnFn = full_attention
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    # fold the preprocess normalize affine into the patch embedding
    # (models/stem_fold.py): the model then takes RAW cropped 0..255
    # inputs; same parameter tree, mathematically identical outputs
    fold_preprocess: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        b, h, w, _ = x.shape
        if h % self.patch or w % self.patch:
            raise ValueError(f"image {h}x{w} not divisible by "
                             f"patch {self.patch}")
        if self.fold_preprocess:
            from idunno_tpu.models.stem_fold import FoldedStemConv
            x = FoldedStemConv(self.dim, (self.patch, self.patch),
                               strides=(self.patch, self.patch),
                               padding=((0, 0), (0, 0)), use_bias=True,
                               dtype=self.dtype,
                               param_dtype=self.param_dtype,
                               name="embed")(x.astype(self.dtype))
        else:
            x = nn.Conv(self.dim, (self.patch, self.patch),
                        strides=(self.patch, self.patch), padding="VALID",
                        dtype=self.dtype, param_dtype=self.param_dtype,
                        name="embed")(x.astype(self.dtype))
        n = (h // self.patch) * (w // self.patch)
        x = x.reshape(b, n, self.dim)
        cls = self.param("cls", nn.initializers.zeros, (1, 1, self.dim),
                         self.param_dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, self.dim)).astype(
            self.dtype), x], axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, n + 1, self.dim), self.param_dtype)
        x = x + pos.astype(self.dtype)
        for i in range(self.depth):
            x = Block(self.dim, self.num_heads, causal=False,
                      attn_fn=self.attn_fn, use_rope=False,
                      dtype=self.dtype, param_dtype=self.param_dtype,
                      name=f"block{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                         name="ln_f")(x)
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          param_dtype=self.param_dtype, name="head")(x[:, 0])
        return logits.astype(jnp.float32)


def vit_s16(**kwargs) -> ViT:
    return ViT(**kwargs)


def vit_tiny(**kwargs) -> ViT:
    """ViT-Ti/16 — small enough for CPU-mesh tests."""
    kwargs.setdefault("dim", 192)
    kwargs.setdefault("depth", 4)
    kwargs.setdefault("num_heads", 3)
    return ViT(**kwargs)
