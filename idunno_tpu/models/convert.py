"""Torchvision → Flax weight conversion (pretrained-weight import).

The reference gets pretrained weights by calling ``torch.hub.load(...,
pretrained=True)`` on every task (`alexnet_resnet.py:17-22`), which needs
network access. Here conversion is a one-time, *optional* step: if a
torchvision checkpoint is available locally (cached hub dir or a state-dict
file), convert it into our Flax variable tree and persist it via the engine's
checkpoint path; otherwise models run with deterministic random init (accuracy
parity then needs the converted weights, throughput does not).

Layout notes:
- torch convs are OIHW; Flax convs are HWIO  → transpose (2, 3, 1, 0).
- torch Linear is (out, in); Flax Dense is (in, out) → transpose.
- AlexNet's first FC consumes a flattened feature map: torch flattens CHW,
  our NHWC model flattens HWC — rows of fc0's weight must be permuted from
  C-major to HWC order.
"""
from __future__ import annotations

from typing import Any

import numpy as np


def _t_conv(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (2, 3, 1, 0))


def _t_dense(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (1, 0))


def _chw_to_hwc_rows(w: np.ndarray, c: int, h: int, wdim: int) -> np.ndarray:
    """Permute a torch Linear weight's input dim from CHW to HWC flattening."""
    out_f, in_f = w.shape
    assert in_f == c * h * wdim
    w = w.reshape(out_f, c, h, wdim).transpose(0, 2, 3, 1).reshape(out_f, in_f)
    return w


def _np(t: Any) -> np.ndarray:
    return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t,
                      dtype=np.float32)


def convert_resnet18(state_dict: dict[str, Any]) -> dict:
    """torchvision ``resnet18`` state_dict → our ResNet variables
    ({'params': ..., 'batch_stats': ...})."""
    sd = {k: _np(v) for k, v in state_dict.items()}
    params: dict[str, Any] = {}
    stats: dict[str, Any] = {}

    def put(tree, path, leaf):
        node = tree
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = leaf

    def bn(flax_name, torch_prefix):
        put(params, (flax_name, "scale"), sd[f"{torch_prefix}.weight"])
        put(params, (flax_name, "bias"), sd[f"{torch_prefix}.bias"])
        put(stats, (flax_name, "mean"), sd[f"{torch_prefix}.running_mean"])
        put(stats, (flax_name, "var"), sd[f"{torch_prefix}.running_var"])

    put(params, ("stem_conv", "kernel"), _t_conv(sd["conv1.weight"]))
    bn("stem_norm", "bn1")
    for stage in range(4):
        for block in range(2):
            tp = f"layer{stage + 1}.{block}"
            fb = f"stage{stage}_block{block}"
            put(params, (fb, "Conv_0", "kernel"), _t_conv(sd[f"{tp}.conv1.weight"]))
            bn_tree_name = (fb, "BatchNorm_0")
            put(params, (*bn_tree_name, "scale"), sd[f"{tp}.bn1.weight"])
            put(params, (*bn_tree_name, "bias"), sd[f"{tp}.bn1.bias"])
            put(stats, (*bn_tree_name, "mean"), sd[f"{tp}.bn1.running_mean"])
            put(stats, (*bn_tree_name, "var"), sd[f"{tp}.bn1.running_var"])
            put(params, (fb, "Conv_1", "kernel"), _t_conv(sd[f"{tp}.conv2.weight"]))
            bn2 = (fb, "BatchNorm_1")
            put(params, (*bn2, "scale"), sd[f"{tp}.bn2.weight"])
            put(params, (*bn2, "bias"), sd[f"{tp}.bn2.bias"])
            put(stats, (*bn2, "mean"), sd[f"{tp}.bn2.running_mean"])
            put(stats, (*bn2, "var"), sd[f"{tp}.bn2.running_var"])
            if f"{tp}.downsample.0.weight" in sd:
                put(params, (fb, "downsample_conv", "kernel"),
                    _t_conv(sd[f"{tp}.downsample.0.weight"]))
                ds = (fb, "downsample_norm")
                put(params, (*ds, "scale"), sd[f"{tp}.downsample.1.weight"])
                put(params, (*ds, "bias"), sd[f"{tp}.downsample.1.bias"])
                put(stats, (*ds, "mean"), sd[f"{tp}.downsample.1.running_mean"])
                put(stats, (*ds, "var"), sd[f"{tp}.downsample.1.running_var"])
    put(params, ("fc", "kernel"), _t_dense(sd["fc.weight"]))
    put(params, ("fc", "bias"), sd["fc.bias"])
    return {"params": params, "batch_stats": stats}


def convert_alexnet(state_dict: dict[str, Any]) -> dict:
    """torchvision ``alexnet`` state_dict → our AlexNet variables."""
    sd = {k: _np(v) for k, v in state_dict.items()}
    params: dict[str, Any] = {}
    conv_map = ["features.0", "features.3", "features.6", "features.8",
                "features.10"]
    for i, tp in enumerate(conv_map):
        params[f"conv{i}"] = {"kernel": _t_conv(sd[f"{tp}.weight"]),
                              "bias": sd[f"{tp}.bias"]}
    fc_map = ["classifier.1", "classifier.4", "classifier.6"]
    for i, tp in enumerate(fc_map):
        w = sd[f"{tp}.weight"]
        if i == 0:
            w = _chw_to_hwc_rows(w, c=256, h=6, wdim=6)
        params[f"fc{i}"] = {"kernel": _t_dense(w), "bias": sd[f"{tp}.bias"]}
    return {"params": params}


def _cached_checkpoint(url: str) -> str | None:
    """Path of an already-downloaded torch-hub checkpoint for ``url``, or
    None. Never touches the network."""
    import os

    try:
        import torch
        hub_dir = torch.hub.get_dir()
    except Exception:
        return None
    fname = url.rsplit("/", 1)[-1]
    path = os.path.join(hub_dir, "checkpoints", fname)
    return path if os.path.exists(path) else None


def try_load_torchvision(model_name: str) -> dict | None:
    """Best-effort *local* pretrained import: convert a torchvision
    checkpoint only if it is already in the torch-hub cache. Returns the
    converted Flax variables, or None when torch/torchvision is missing or
    nothing is cached — zero-egress environments must never block on a
    download attempt."""
    try:
        import torch
        from torchvision import models as tvm
    except Exception:
        return None
    if model_name == "alexnet":
        weights, convert = tvm.AlexNet_Weights.IMAGENET1K_V1, convert_alexnet
    elif model_name in ("resnet", "resnet18"):
        weights, convert = tvm.ResNet18_Weights.IMAGENET1K_V1, convert_resnet18
    else:
        return None
    path = _cached_checkpoint(weights.url)
    if path is None:
        return None
    # conversion errors propagate: silently falling back to random weights
    # while claiming "pretrained" would produce garbage predictions.
    state_dict = torch.load(path, map_location="cpu", weights_only=True)
    return convert(state_dict)
