"""Torchvision → Flax weight conversion (pretrained-weight import).

The reference gets pretrained weights by calling ``torch.hub.load(...,
pretrained=True)`` on every task (`alexnet_resnet.py:17-22`), which needs
network access. Here conversion is a one-time, *optional* step: if a
torchvision checkpoint is available locally (cached hub dir or a state-dict
file), convert it into our Flax variable tree and persist it via the engine's
checkpoint path; otherwise models run with deterministic random init (accuracy
parity then needs the converted weights, throughput does not).

Layout notes:
- torch convs are OIHW; Flax convs are HWIO  → transpose (2, 3, 1, 0).
- torch Linear is (out, in); Flax Dense is (in, out) → transpose.
- AlexNet's first FC consumes a flattened feature map: torch flattens CHW,
  our NHWC model flattens HWC — rows of fc0's weight must be permuted from
  C-major to HWC order.
"""
from __future__ import annotations

from typing import Any

import numpy as np


def _t_conv(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (2, 3, 1, 0))


def _t_dense(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (1, 0))


def _chw_to_hwc_rows(w: np.ndarray, c: int, h: int, wdim: int) -> np.ndarray:
    """Permute a torch Linear weight's input dim from CHW to HWC flattening."""
    out_f, in_f = w.shape
    assert in_f == c * h * wdim
    w = w.reshape(out_f, c, h, wdim).transpose(0, 2, 3, 1).reshape(out_f, in_f)
    return w


def _np(t: Any) -> np.ndarray:
    return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t,
                      dtype=np.float32)


def convert_resnet(state_dict: dict[str, Any],
                   stage_sizes: tuple = (2, 2, 2, 2),
                   convs_per_block: int = 2) -> dict:
    """torchvision ResNet state_dict → our ResNet variables
    ({'params': ..., 'batch_stats': ...}). ``convs_per_block`` is 2 for
    BasicBlock (18/34) and 3 for Bottleneck (50/101/152) — the per-block
    conv/bn key pattern (convN/bnN, downsample.0/1) is identical."""
    sd = {k: _np(v) for k, v in state_dict.items()}
    params: dict[str, Any] = {}
    stats: dict[str, Any] = {}

    def put(tree, path, leaf):
        node = tree
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = leaf

    def bn(tree_path, torch_prefix):
        put(params, (*tree_path, "scale"), sd[f"{torch_prefix}.weight"])
        put(params, (*tree_path, "bias"), sd[f"{torch_prefix}.bias"])
        put(stats, (*tree_path, "mean"), sd[f"{torch_prefix}.running_mean"])
        put(stats, (*tree_path, "var"), sd[f"{torch_prefix}.running_var"])

    put(params, ("stem_conv", "kernel"), _t_conv(sd["conv1.weight"]))
    bn(("stem_norm",), "bn1")
    for stage, n_blocks in enumerate(stage_sizes):
        for block in range(n_blocks):
            tp = f"layer{stage + 1}.{block}"
            fb = f"stage{stage}_block{block}"
            for c in range(convs_per_block):
                put(params, (fb, f"Conv_{c}", "kernel"),
                    _t_conv(sd[f"{tp}.conv{c + 1}.weight"]))
                bn((fb, f"BatchNorm_{c}"), f"{tp}.bn{c + 1}")
            if f"{tp}.downsample.0.weight" in sd:
                put(params, (fb, "downsample_conv", "kernel"),
                    _t_conv(sd[f"{tp}.downsample.0.weight"]))
                bn((fb, "downsample_norm"), f"{tp}.downsample.1")
    put(params, ("fc", "kernel"), _t_dense(sd["fc.weight"]))
    put(params, ("fc", "bias"), sd["fc.bias"])
    return {"params": params, "batch_stats": stats}


def convert_resnet18(state_dict: dict[str, Any]) -> dict:
    """torchvision ``resnet18`` state_dict → our ResNet-18 variables."""
    return convert_resnet(state_dict, (2, 2, 2, 2), convs_per_block=2)


def convert_resnet50(state_dict: dict[str, Any]) -> dict:
    """torchvision ``resnet50`` state_dict → our ResNet-50 variables."""
    return convert_resnet(state_dict, (3, 4, 6, 3), convs_per_block=3)


def convert_alexnet(state_dict: dict[str, Any]) -> dict:
    """torchvision ``alexnet`` state_dict → our AlexNet variables."""
    sd = {k: _np(v) for k, v in state_dict.items()}
    params: dict[str, Any] = {}
    conv_map = ["features.0", "features.3", "features.6", "features.8",
                "features.10"]
    for i, tp in enumerate(conv_map):
        params[f"conv{i}"] = {"kernel": _t_conv(sd[f"{tp}.weight"]),
                              "bias": sd[f"{tp}.bias"]}
    fc_map = ["classifier.1", "classifier.4", "classifier.6"]
    for i, tp in enumerate(fc_map):
        w = sd[f"{tp}.weight"]
        if i == 0:
            w = _chw_to_hwc_rows(w, c=256, h=6, wdim=6)
        params[f"fc{i}"] = {"kernel": _t_dense(w), "bias": sd[f"{tp}.bias"]}
    return {"params": params}


def _cached_checkpoint(url: str) -> str | None:
    """Path of an already-downloaded torch-hub checkpoint for ``url``, or
    None. Never touches the network."""
    import os

    try:
        import torch
        hub_dir = torch.hub.get_dir()
    except Exception:
        return None
    fname = url.rsplit("/", 1)[-1]
    path = os.path.join(hub_dir, "checkpoints", fname)
    return path if os.path.exists(path) else None


def try_load_torchvision(model_name: str) -> dict | None:
    """Best-effort *local* pretrained import: convert a torchvision
    checkpoint only if it is already in the torch-hub cache. Returns the
    converted Flax variables, or None when torch/torchvision is missing or
    nothing is cached — zero-egress environments must never block on a
    download attempt."""
    try:
        import torch
        from torchvision import models as tvm
    except Exception:
        return None
    if model_name == "alexnet":
        weights, convert = tvm.AlexNet_Weights.IMAGENET1K_V1, convert_alexnet
    elif model_name in ("resnet", "resnet18"):
        weights, convert = tvm.ResNet18_Weights.IMAGENET1K_V1, convert_resnet18
    elif model_name == "resnet50":
        # V1 on purpose: the serving preprocess is the reference's
        # Resize(256)/CenterCrop(224) recipe, which matches V1 weights
        # (V2 checkpoints expect a 232-resize and would lose accuracy)
        weights, convert = tvm.ResNet50_Weights.IMAGENET1K_V1, convert_resnet50
    else:
        return None
    path = _cached_checkpoint(weights.url)
    if path is None:
        return None
    # conversion errors propagate: silently falling back to random weights
    # while claiming "pretrained" would produce garbage predictions.
    state_dict = torch.load(path, map_location="cpu", weights_only=True)
    return convert(state_dict)
