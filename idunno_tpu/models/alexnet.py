"""AlexNet as a Flax module, TPU-first.

Replaces the reference's per-task ``torch.hub.load('pytorch/vision', 'alexnet')``
(`alexnet_resnet.py:17-19`). Architecture matches torchvision ``alexnet``
(the single-tower variant): five convs, three maxpools, adaptive pool to 6x6,
three FC layers with dropout. NHWC layout, bfloat16 compute, float32 params.
"""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class AlexNet(nn.Module):
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.5
    # fold the preprocess normalize affine into conv0
    # (models/stem_fold.py): the model then takes RAW cropped 0..255
    # inputs; same parameter tree, mathematically identical outputs
    fold_preprocess: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = lambda feat, kern, stride, pad, name: nn.Conv(
            feat, kern, strides=stride, padding=pad,
            dtype=self.dtype, param_dtype=self.param_dtype, name=name)
        x = x.astype(self.dtype)
        if self.fold_preprocess:
            from idunno_tpu.models.stem_fold import FoldedStemConv
            x = nn.relu(FoldedStemConv(
                64, (11, 11), strides=(4, 4), padding=((2, 2), (2, 2)),
                use_bias=True, dtype=self.dtype,
                param_dtype=self.param_dtype, name="conv0")(x))
        else:
            x = nn.relu(conv(64, (11, 11), (4, 4), ((2, 2), (2, 2)),
                             "conv0")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(192, (5, 5), (1, 1), ((2, 2), (2, 2)), "conv1")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(384, (3, 3), (1, 1), ((1, 1), (1, 1)), "conv2")(x))
        x = nn.relu(conv(256, (3, 3), (1, 1), ((1, 1), (1, 1)), "conv3")(x))
        x = nn.relu(conv(256, (3, 3), (1, 1), ((1, 1), (1, 1)), "conv4")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # torchvision AdaptiveAvgPool2d((6,6)); identity at 224x224 input.
        from idunno_tpu.ops.pooling import adaptive_avg_pool
        x = adaptive_avg_pool(x, (6, 6))
        x = x.reshape((x.shape[0], -1))
        dense = lambda feat, name: nn.Dense(
            feat, dtype=self.dtype, param_dtype=self.param_dtype, name=name)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(dense(4096, "fc0")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(dense(4096, "fc1")(x))
        x = dense(self.num_classes, "fc2")(x)
        return x.astype(jnp.float32)
