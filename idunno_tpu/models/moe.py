"""Mixture-of-experts model family (switch-routed FFN).

The reference's only multi-model mechanism is two whole-model jobs
fair-sharing workers (`mp4_machinelearning.py:501-539`); it has no
conditional computation. This adds a switch-style MoE FFN as a first-class
model family: a learned router picks the top-k experts per token (k=1 the
Switch layer, k=2 the GShard configuration), and the
expert FFNs either all live on every device (``mesh=None``, the dense path
— also the exact ground truth for tests) or are sharded over a mesh axis
with all_to_all dispatch (`idunno_tpu.parallel.expert`).

``MoETransformerLM`` is `idunno_tpu.models.transformer.TransformerLM` with
the switch FFN plugged in via ``ffn_factory`` — by default on every block;
``moe_every=2`` gives the Switch-Transformer every-other-block layout. It
therefore composes with ring / Ulysses sequence parallelism for free.

Training: top-1 routing collapses without pressure toward balance, so the
layer sows the Switch-Transformer auxiliary load-balancing loss
(E · Σ_e frac_routed_e · mean_prob_e) into the ``"losses"`` collection;
``moe_aux_loss`` sums it for adding to the task loss.
"""
from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from idunno_tpu.parallel.expert import (
    EXPERT_AXIS, expert_parallel_apply, switch_dispatch)
from idunno_tpu.models.transformer import AttnFn, TransformerLM
from idunno_tpu.parallel.ring_attention import full_attention


class SwitchFFN(nn.Module):
    """Top-k routed expert FFN. Input/output [B, T, dim].

    ``k=1`` is the Switch-Transformer layer (gate = raw top prob); ``k>1``
    is GShard-style top-k routing: each token is sent to its k best experts
    with gates renormalised over the chosen k. Routing-to-dispatch reuses
    the top-1 machinery by treating each (token, choice) pair as its own
    routing unit — capacity then naturally accounts for all k streams."""

    dim: int
    hidden: int
    n_experts: int
    k: int = 1
    capacity_factor: float = 2.0
    mesh: Mesh | None = None            # None → dense (all experts local)
    axis: str = EXPERT_AXIS
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    def _expert_params(self):
        e, d, h = self.n_experts, self.dim, self.hidden
        init = nn.initializers.lecun_normal()
        return {
            "w1": self.param("w1", init, (e, d, h), self.param_dtype),
            "b1": self.param("b1", nn.initializers.zeros, (e, h),
                             self.param_dtype),
            "w2": self.param("w2", init, (e, h, d), self.param_dtype),
            "b2": self.param("b2", nn.initializers.zeros, (e, d),
                             self.param_dtype),
        }

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        n = b * t
        router = nn.Dense(self.n_experts, dtype=jnp.float32,
                          param_dtype=self.param_dtype, name="router")
        if not 1 <= self.k <= self.n_experts:
            raise ValueError(f"k={self.k}: want 1..{self.n_experts}")
        probs = jax.nn.softmax(router(x.astype(jnp.float32)).reshape(
            n, self.n_experts))
        topk_w, topk_idx = jax.lax.top_k(probs, self.k)        # [n, k]
        if self.k == 1:
            gate_idx, gate_w = topk_idx[:, 0], topk_w[:, 0]    # switch
        else:
            # GShard top-k: renormalise the chosen gates; flatten so every
            # (token, choice) is one routing unit in dispatch order
            # [t0c0, t0c1, ..., t1c0, ...] (stays aligned with
            # jnp.repeat(flat, k) below and with contiguous token sharding).
            topk_w = topk_w / topk_w.sum(axis=-1, keepdims=True)
            gate_idx, gate_w = topk_idx.reshape(-1), topk_w.reshape(-1)

        # Switch-Transformer load-balance loss: E · Σ_e f_e · P_e with f_e
        # the top-1 routing fraction, minimized (=1) at uniform routing.
        # Without it routing collapses onto one expert and capacity drops
        # kill most tokens' FFN output.
        frac = jax.nn.one_hot(topk_idx[:, 0], self.n_experts).mean(axis=0)
        aux = self.n_experts * jnp.sum(frac * probs.mean(axis=0))
        self.sow("losses", "moe_aux", aux)

        params = self._expert_params()
        flat = x.reshape(n, d)
        if self.k > 1:
            flat = jnp.repeat(flat, self.k, axis=0)            # [n*k, d]
        n_units = n * self.k

        def expert_fn(p, toks):
            h = jnp.einsum("cd,dh->ch", toks.astype(self.dtype),
                           p["w1"].astype(self.dtype)) + p["b1"]
            return (jnp.einsum("ch,hd->cd", nn.gelu(h),
                               p["w2"].astype(self.dtype))
                    + p["b2"]).astype(jnp.float32)

        if self.mesh is not None:
            p_sz = self.mesh.shape[self.axis]
            cap = self._capacity(n_units // p_sz)
            out = expert_parallel_apply(expert_fn, params, flat, gate_idx,
                                        gate_w, self.mesh, axis=self.axis,
                                        capacity=cap)
        else:
            dispatch, combine = switch_dispatch(
                gate_idx, gate_w, self.n_experts, self._capacity(n_units))
            buf = jnp.einsum("nec,nd->ecd", dispatch, flat)
            done = jax.vmap(expert_fn)(params, buf)
            out = jnp.einsum("ecd,nec->nd", done, combine)
        if self.k > 1:
            out = out.reshape(n, self.k, d).sum(axis=1)        # combine k
        return out.reshape(b, t, d).astype(x.dtype)

    def _capacity(self, tokens_per_shard: int) -> int:
        # floor at k: one token's k choices can all land on one expert, and
        # for tiny token counts (single-token decode steps) the proportional
        # capacity would otherwise guarantee dropped streams
        return max(self.k, int(self.capacity_factor * tokens_per_shard
                               / self.n_experts))


def switch_ffn_factory(n_experts: int, capacity_factor: float = 2.0,
                       mesh: Mesh | None = None, axis: str = EXPERT_AXIS,
                       hidden_ratio: int = 4, k: int = 1):
    """An ``ffn_factory`` for `Block`/`TransformerLM` that builds a
    SwitchFFN in place of the dense MLP."""
    def make(dim: int, dtype, param_dtype, name: str) -> nn.Module:
        return SwitchFFN(dim=dim, hidden=dim * hidden_ratio,
                         n_experts=n_experts, k=k,
                         capacity_factor=capacity_factor, mesh=mesh,
                         axis=axis, dtype=dtype, param_dtype=param_dtype,
                         name=name)
    # declarative twin of this factory so `engine.generate.save_lm` can
    # persist MoE architectures: everything here is data; the mesh is CODE
    # and deliberately absent — loaders reconstruct dense (mesh=None) and
    # re-apply expert parallelism themselves if they want it
    make.lm_store_ffn = {"kind": "switch", "n_experts": n_experts,
                         "capacity_factor": capacity_factor,
                         "hidden_ratio": hidden_ratio, "k": k}
    return make


def MoETransformerLM(vocab: int = 1024, dim: int = 128, depth: int = 2,
                     num_heads: int = 4, n_experts: int = 4,
                     capacity_factor: float = 2.0, causal: bool = True,
                     attn_fn: AttnFn = full_attention,
                     mesh: Mesh | None = None, axis: str = EXPERT_AXIS,
                     moe_every: int = 1, hidden_ratio: int = 4, k: int = 1,
                     remat: bool = False,
                     dtype=jnp.float32, param_dtype=jnp.float32
                     ) -> TransformerLM:
    """Causal LM with switch-MoE FFNs — `TransformerLM` with the expert
    layer plugged in every ``moe_every``-th block (1 = all blocks, 2 = the
    Switch-Transformer interleave); ``k`` routes each token to its top-k
    experts (GShard top-2 when k=2)."""
    return TransformerLM(
        vocab=vocab, dim=dim, depth=depth, num_heads=num_heads,
        causal=causal, attn_fn=attn_fn,
        ffn_factory=switch_ffn_factory(n_experts, capacity_factor, mesh,
                                       axis, hidden_ratio, k=k),
        ffn_every=moe_every, remat=remat,
        dtype=dtype, param_dtype=param_dtype)


def moe_aux_loss(mutated_collections) -> jnp.ndarray:
    """Sum every sowed ``moe_aux`` entry (one per MoE block): call
    ``apply(..., mutable=["losses"])`` and feed the returned collections."""
    losses = mutated_collections.get("losses", {})
    return sum(jnp.sum(jnp.asarray(leaf))
               for leaf in jax.tree.leaves(losses)) if losses else jnp.asarray(0.0)
