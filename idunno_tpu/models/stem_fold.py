"""Fold the preprocess normalize affine into the stem convolution.

The serving input pipeline is CenterCrop + ToTensor + Normalize
(reference `alexnet_resnet.py:57-62`), i.e. per-channel
``x_norm = x/255·(1/std) - mean/std = a·x + c`` — an affine map feeding a
convolution. The 2026-07-31 batch-256 trace (`TRACE_BS256.json`) showed
~15% of device step time spent on the slice→reshape→layout-copy chains
XLA inserts around the Pallas preprocess custom-call that materializes
``a·x + c``; this module removes the materialization entirely by folding
the affine into the stem conv (linearity):

    conv(pad0(a·x + c·1_img), W) = conv(pad0(x), W·a) + conv(pad0(c·1), W)

The first term scales each input-channel slice of the KERNEL (free: done
in param dtype at apply time, [kh, kw, 3, F] work); the second is a
constant map — computed as a conv over a single c-valued image, so the
zero-padding borders match the unfolded path EXACTLY (the padded region
contributes nothing in either form). The network then consumes the raw
cropped uint8 values (cast to the compute dtype — integers 0..255 are
exact in bf16), and the only elementwise op left at the boundary is that
cast, which XLA fuses into the conv's input read.

The PARAMETER stays the torchvision-shaped ``(kh, kw, 3, F)`` kernel (+
bias where the family has one) under the family's usual stem name —
converters, checkpoints and parity tests see an identical tree (same
discipline as `models/resnet._S2DStem`). Folding changes only where the
``a`` multiply happens (weights, in f32, vs activations), so outputs are
mathematically identical and numerically equal to within bf16 rounding.
"""
from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from idunno_tpu.ops.preprocess import IMAGENET_MEAN, IMAGENET_STD


class FoldedStemConv(nn.Module):
    """Drop-in stem conv over RAW 0..255 inputs, torchvision param tree.

    Name it as the family's stem (``stem_conv``/``conv0``/``embed``) and it
    creates the identical ``kernel`` (and ``bias``) params nn.Conv would,
    but computes ``conv(normalize(x), kernel) [+ bias]`` from the raw
    input via the folded form above."""

    features: int
    kernel_size: tuple[int, int]
    strides: tuple[int, int]
    padding: tuple[tuple[int, int], tuple[int, int]]
    use_bias: bool
    dtype: jnp.dtype
    param_dtype: jnp.dtype
    mean: tuple[float, ...] = IMAGENET_MEAN
    std: tuple[float, ...] = IMAGENET_STD

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        if c != len(self.mean):
            raise ValueError(f"folded stem expects {len(self.mean)} input "
                             f"channels, got {c}")
        kh, kw = self.kernel_size
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (kh, kw, c, self.features), self.param_dtype)
        a = 1.0 / (255.0 * np.asarray(self.std))          # [C]
        cc = -np.asarray(self.mean) / np.asarray(self.std)

        def conv(inp, kern):
            return jax.lax.conv_general_dilated(
                inp, kern, window_strides=self.strides,
                padding=self.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        # scaled-kernel term in param dtype, cast once (exactly where
        # nn.Conv casts its kernel)
        ks = (kernel * jnp.asarray(a, self.param_dtype)[None, None, :, None]
              ).astype(self.dtype)
        y = conv(x.astype(self.dtype), ks)
        # constant-map term: one c-valued image through the UNSCALED
        # kernel; zero padding reproduces the unfolded borders exactly.
        # [1, Ho, Wo, F] — broadcasts over the batch; XLA folds the tiny
        # conv into a constant-per-dispatch when the params are donated
        cimg = jnp.broadcast_to(jnp.asarray(cc, self.dtype), (1, h, w, c))
        y = y + conv(cimg, kernel.astype(self.dtype))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), self.param_dtype)
            y = y + bias.astype(self.dtype)
        return y
