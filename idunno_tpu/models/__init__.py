"""Model registry.

The reference supports exactly two model names, dispatched by string
(`alexnet_resnet.py:17-22`, `mp4_machinelearning.py:560-571`): ``alexnet``
and ``resnet`` (ResNet-18). We keep those names as the registry keys and make
the registry extensible.
"""
from __future__ import annotations

from collections.abc import Callable

import flax.linen as nn

from idunno_tpu.models.alexnet import AlexNet
from idunno_tpu.models.resnet import ResNet, resnet18, resnet34, resnet50
from idunno_tpu.models.vit import ViT, vit_s16, vit_tiny

_REGISTRY: dict[str, Callable[..., nn.Module]] = {
    "alexnet": AlexNet,
    "resnet": resnet18,      # the reference's "resnet" means ResNet-18
    "resnet18": resnet18,
    "resnet34": resnet34,
    "resnet50": resnet50,
    "vit": vit_s16,
    "vit_tiny": vit_tiny,
}


def available_models() -> list[str]:
    return sorted(_REGISTRY)


def create_model(name: str, **kwargs) -> nn.Module:
    try:
        return _REGISTRY[name](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; available: {available_models()}") from None


def register_model(name: str, factory: Callable[..., nn.Module]) -> None:
    _REGISTRY[name] = factory


__all__ = ["AlexNet", "ResNet", "ViT", "resnet18", "resnet34", "vit_s16",
           "vit_tiny", "create_model", "available_models", "register_model"]
