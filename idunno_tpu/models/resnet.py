"""ResNet family as Flax modules, TPU-first.

Replaces the reference's per-task ``torch.hub.load('pytorch/vision', 'resnet18')``
(`alexnet_resnet.py:21-22`) with modules whose parameters are initialised (or
converted from torchvision, see `models/convert.py`) exactly once and stay
resident in HBM. Layout is NHWC (XLA's preferred TPU conv layout), compute in
bfloat16 so convolutions tile onto the MXU, params in float32.

Architectures match torchvision: stem conv7x7/2 + maxpool, four stages of
BasicBlocks (resnet18: [2,2,2,2], resnet34: [3,4,6,3]) or Bottlenecks
(resnet50: [3,4,6,3], 4× expansion, stride on the 3x3 — the v1.5 layout),
stride-2 projection downsample at stage entry, global average pool,
1000-way FC.
"""
from __future__ import annotations

from collections.abc import Callable, Sequence
from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Callable[..., nn.Module]


class _S2DStem(nn.Module):
    """Space-to-depth stem: the 7x7/stride-2 conv on [H, W, 3] recast as a
    4x4/stride-1 conv on the 2x2-space-to-depth input [H/2, W/2, 12] — the
    standard MLPerf-ResNet TPU trick. The 3-channel stride-2 stem is the
    worst-shaped conv in the network for the 128x128 MXU; the recast form
    contracts 4*4*12=192 instead of 7*7*3=147 per tap with no stride.

    The PARAMETER is still the torchvision-shaped (7, 7, C, F) kernel under
    the same ``stem_conv/kernel`` path — converters, checkpoints and parity
    tests see an identical tree — and the recast runs at apply time:
    zero-pad 7->8 with one LEADING row/column (tap index a = 2m + dy - 1,
    so a = -1, never a = 7, is the empty slot), then fold each 2x2 spatial
    block into channels. Output matches the 7x7 form exactly (same taps,
    same zero padding, reassociated summation only)."""

    features: int
    dtype: jnp.dtype
    param_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        if h % 2 or w % 2:
            raise ValueError(f"space-to-depth stem needs even spatial dims, "
                             f"got {h}x{w}")
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (7, 7, c, self.features), self.param_dtype)
        # taps: out(i,j) reads u = 2i + a - 3 = 2(i - 2 + m) + dy
        #   => a = 2m + dy - 1, m in 0..3, dy in {0,1}
        kp = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))  # [8,8,C,F]
        kp = kp.reshape(4, 2, 4, 2, c, self.features)
        kp = kp.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c,
                                                    self.features)
        xs = x.reshape(b, h // 2, 2, w // 2, 2, c)
        xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2,
                                                    4 * c)
        return jax.lax.conv_general_dilated(
            xs.astype(self.dtype), kp.astype(self.dtype),
            window_strides=(1, 1), padding=((2, 1), (2, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class BasicBlock(nn.Module):
    """Two 3x3 convs with a residual connection (torchvision BasicBlock)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        pad1 = ((1, 1), (1, 1))   # torch-style explicit padding, not XLA SAME
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                      padding=pad1)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), padding=pad1)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 strides=(self.strides, self.strides),
                                 padding="VALID", name="downsample_conv")(residual)
            residual = self.norm(name="downsample_norm")(residual)
        return nn.relu(residual + y)


class Bottleneck(nn.Module):
    """1x1 reduce → 3x3 (strided, torchvision v1.5 placement) → 1x1 expand
    (4×), residual with projection on shape change (torchvision
    Bottleneck)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    expansion: int = 4

    @nn.compact
    def __call__(self, x):
        residual = x
        out_ch = self.filters * self.expansion
        y = self.conv(self.filters, (1, 1), padding="VALID")(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3),
                      strides=(self.strides, self.strides),
                      padding=((1, 1), (1, 1)))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(out_ch, (1, 1), padding="VALID")(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(out_ch, (1, 1),
                                 strides=(self.strides, self.strides),
                                 padding="VALID",
                                 name="downsample_conv")(residual)
            residual = self.norm(name="downsample_norm")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Generic ResNet: BasicBlock (18 = [2,2,2,2], 34 = [3,4,6,3]) or
    Bottleneck (50 = [3,4,6,3] with ``block_cls=Bottleneck``)."""

    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    block_cls: ModuleDef = BasicBlock
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    # run the stem as a space-to-depth 4x4/s1 conv (see _S2DStem) — same
    # parameters, same outputs, better MXU shape; opt-in until measured
    stem_s2d: bool = False
    # fold the preprocess normalize affine into the stem conv
    # (models/stem_fold.py): the model then takes RAW cropped 0..255
    # inputs; same parameter tree, mathematically identical outputs
    fold_preprocess: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(nn.Conv, use_bias=False,
                       dtype=self.dtype, param_dtype=self.param_dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5,
                       dtype=self.dtype, param_dtype=self.param_dtype)

        if self.stem_s2d and self.fold_preprocess:
            raise ValueError("stem_s2d and fold_preprocess both recast the "
                             "stem conv; pick one")
        x = x.astype(self.dtype)
        if self.fold_preprocess:
            from idunno_tpu.models.stem_fold import FoldedStemConv
            x = FoldedStemConv(self.num_filters, (7, 7), strides=(2, 2),
                               padding=((3, 3), (3, 3)), use_bias=False,
                               dtype=self.dtype,
                               param_dtype=self.param_dtype,
                               name="stem_conv")(x)
        elif self.stem_s2d:
            x = _S2DStem(self.num_filters, dtype=self.dtype,
                         param_dtype=self.param_dtype,
                         name="stem_conv")(x)
        else:
            x = conv(self.num_filters, (7, 7), strides=(2, 2),
                     padding=((3, 3), (3, 3)), name="stem_conv")(x)
        x = norm(name="stem_norm")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = self.block_cls(self.num_filters * 2 ** stage, strides,
                                   conv=conv, norm=norm,
                                   name=f"stage{stage}_block{block}")(x)
        x = jnp.mean(x, axis=(1, 2))            # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="fc")(x)
        return x.astype(jnp.float32)            # logits in f32 for stable softmax


def resnet18(**kwargs) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), **kwargs)


def resnet34(**kwargs) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), **kwargs)


def resnet50(**kwargs) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=Bottleneck, **kwargs)
