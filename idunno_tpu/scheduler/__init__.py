from idunno_tpu.scheduler.tasks import Task, TaskBook  # noqa: F401
from idunno_tpu.scheduler.fair import FairScheduler, fair_shares  # noqa: F401
