"""Fair-time scheduling across concurrent model jobs.

Reference semantics (`assign_inference_work`, `mp4_machinelearning.py
:501-539`): with two jobs, each model gets ``round(t_m / (t_a + t_r) *
RATE_FACTOR)`` workers, clamped to the alive-worker count, where ``t_m`` is
the model's measured average query time — i.e. *resources proportional to
per-query cost*, so both jobs make equal progress in wall-clock time
(fair TIME sharing). Workers for each job are drawn by ``random.sample``
from the alive set independently per job (jobs may time-share a worker), and
the query range is split contiguously and near-evenly (`:516-536`).

Generalisations here: any number of concurrent models (the two-model formula
is the N=2 case of proportional shares); injected seeded RNG so scheduling is
reproducible (the reference's bare ``random.sample`` is not, `:520`); at
least one worker per active job so a new job is never starved before it has
timing history.
"""
from __future__ import annotations

import random
import time
from collections.abc import Callable

from idunno_tpu.config import ClusterConfig
from idunno_tpu.scheduler.tasks import Task, TaskBook


def fair_shares(avg_query_time: dict[str, float], rate_factor: int,
                n_workers: int) -> dict[str, int]:
    """Workers per model, proportional to measured per-query time; models
    with no history yet weigh as the mean of the others (ratio 1.0 in the
    reference when resnet has no data, `:504-506`)."""
    if not avg_query_time:
        return {}
    known = [t for t in avg_query_time.values() if t > 0]
    default = sum(known) / len(known) if known else 1.0
    weights = {m: (t if t > 0 else default)
               for m, t in avg_query_time.items()}
    total = sum(weights.values())
    shares = {}
    for m, w in weights.items():
        n = round(w / total * rate_factor)
        shares[m] = max(min(n, n_workers), 1 if n_workers else 0)
    return shares


def heterogeneous_shares(cnn_query_s: dict[str, float],
                         lm_request_s: dict[str, float],
                         rate_factor: int,
                         n_workers: int) -> dict[str, int]:
    """The reference's two-model ratio formula (`mp4_machinelearning.py
    :501-539`) generalized across JOB TYPES: CNN query jobs (measured avg
    seconds per query) and LM decode pools (measured avg seconds per
    request) divide the cluster's worker units proportionally to measured
    per-unit cost, so every job — whatever its type — makes equal
    wall-clock progress. Keys come back namespaced ``cnn:<model>`` /
    ``lm:<pool>``; a job with no history yet weighs as the mean of the
    others, exactly like the reference's no-data ratio 1.0."""
    times = {f"cnn:{m}": t for m, t in cnn_query_s.items()}
    times.update({f"lm:{p}": t for p, t in lm_request_s.items()})
    return fair_shares(times, rate_factor, n_workers)


def split_range(start: int, end: int, workers: list[str]) -> list[tuple[str, int, int]]:
    """Contiguous near-even split of the inclusive range across workers
    (`:523-536`: per step, round(remaining_items / remaining_workers))."""
    out = []
    remaining = end - start + 1
    cursor = start
    for i, w in enumerate(workers):
        n = round(remaining / (len(workers) - i))
        if n <= 0:
            continue
        out.append((w, cursor, cursor + n - 1))
        cursor += n
        remaining -= n
    return out


class FairScheduler:
    """Coordinator-side assignment engine over a TaskBook."""

    def __init__(self, config: ClusterConfig,
                 rng: random.Random | None = None,
                 clock: Callable[[], float] = time.time) -> None:
        self.config = config
        self.rng = rng or random.Random(0)
        self.clock = clock
        self.book = TaskBook()
        # measured avg query seconds per model — fed by the metrics layer
        self.avg_query_time: dict[str, float] = {}
        # non-CNN jobs sharing the cluster (namespaced keys, e.g.
        # "lm:<pool>" → measured avg seconds per request) — fed by the LM
        # pool manager; they weigh in the fair share but are never
        # assigned CNN tasks
        self.extra_jobs: dict[str, float] = {}

    def active_models(self) -> list[str]:
        """Models with unfinished work (the 'concurrent jobs' the fair share
        divides between)."""
        return sorted({t.model for t in self.book.in_flight()})

    def assign(self, model: str, qnum: int, start: int, end: int,
               workers: list[str], dataset: str | None = None) -> list[Task]:
        """Split one query across this model's fair share of workers and
        record the tasks."""
        if not workers:
            return []
        times = dict(self.avg_query_time)
        for m in {model, *self.active_models()}:
            times.setdefault(m, 0.0)
        # heterogeneous arbitration: live LM pools claim their measured
        # share of the worker units, shrinking every CNN job's slice
        # proportionally (reference formula over the job UNION)
        times.update(self.extra_jobs)
        shares = fair_shares(times, self.config.rate_factor, len(workers))
        n = max(1, min(shares.get(model, 1), len(workers),
                       end - start + 1))
        chosen = self.rng.sample(workers, n)
        now = self.clock()
        tasks = [Task(model=model, qnum=qnum, worker=w, start=s, end=e,
                      t_assigned=now, dataset=dataset)
                 for w, s, e in split_range(start, end, chosen)]
        self.book.record(tasks)
        return tasks

    def reassign_failed(self, dead: str, alive: list[str]) -> list[Task]:
        """Reference ``transfer_failed_inference_work`` (`:706-760`): every
        in-flight task on the dead worker moves to its first eligible ring
        successor (round-robin over alive workers here — the ring-successor
        walk with dead/master skips, minus the reference's bias of piling
        everything onto one neighbor). A task already moved
        ``max_task_moves`` times is marked permanently FAILED instead (its
        t_assigned resets on every move, so the straggler cap can never
        catch a job that keeps killing its workers); returns only the
        tasks that actually moved."""
        moved = []
        candidates = [h for h in alive if h != dead]
        if not candidates:
            return []
        now = self.clock()
        for i, task in enumerate(self.book.in_flight(worker=dead)):
            if task.moves >= self.config.max_task_moves:
                self.book.mark_failed(task, now)
                import logging
                logging.getLogger("idunno.scheduler").error(
                    "task %s#%s [%s, %s] FAILED after %d total moves "
                    "(kept losing its workers)", task.model, task.qnum,
                    task.start, task.end, task.moves)
                continue
            successor = self._ring_successor(dead, candidates, i)
            moved.append(self.book.reassign(task, successor, now))
        return moved

    def _ring_successor(self, dead: str, candidates: list[str],
                        offset: int) -> str:
        hosts = self.config.hosts
        if dead in hosts:
            start = hosts.index(dead)
            ring = [hosts[(start + k) % len(hosts)]
                    for k in range(1, len(hosts) + 1)]
            ordered = [h for h in ring if h in candidates]
            if ordered:
                return ordered[offset % len(ordered)]
        return candidates[offset % len(candidates)]

    def stragglers(self) -> list[Task]:
        return self.book.stragglers(self.clock(),
                                    self.config.straggler_timeout_s)

    def redispatch_straggler(self, task: Task, alive: list[str],
                             expected_worker: str | None = None,
                             expected_stamp: float | None = None
                             ) -> Task | None:
        """Move a stuck task to a different alive worker (reference
        `monitor_inference_work` re-sends to the same worker, `:809-830`;
        moving is strictly better when the worker is wedged). These moves —
        and only these — count against the task's retry cap. With an
        expected (worker, stamp) snapshot the move is currency-checked
        (TaskBook.reassign_if_current) and returns None when another
        thread re-booked the task first."""
        others = [h for h in alive if h != task.worker] or alive
        target = self.rng.choice(others)
        if expected_worker is None:
            return self.book.reassign(task, target, self.clock(),
                                      count_retry=True)
        return self.book.reassign_if_current(
            task, expected_worker, expected_stamp, target, self.clock(),
            count_retry=True)
