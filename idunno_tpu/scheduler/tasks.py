"""Task bookkeeping for the coordinator.

The reference keeps two parallel dicts: ``worker_set[(model, qnum)]`` holding
``(vm, start, end, 'w'/'f', t_start, t_end)`` tuples and the reverse map
``working_vm_set[vm]`` (`mp4_machinelearning.py:137-144, 529-533`). Here both
views live behind one thread-safe book with typed tasks, and the whole book
serializes to/from wire form for standby-coordinator state replication
(replacing the stringified-dict broadcast, `:971-1011`).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any

WORKING = "w"        # reference's 'w' / 'f' task states (`:529-533, 645-652`)
FINISHED = "f"
FAILED = "x"         # beyond reference: permanently failed (retry cap hit)


@dataclass
class Task:
    model: str
    qnum: int
    worker: str
    start: int                  # inclusive, reference range convention
    end: int
    state: str = WORKING
    t_assigned: float = 0.0
    t_finished: float = 0.0
    # the query's dataset root travels WITH the task so failure/straggler
    # re-dispatch (and post-failover resumption) reruns it on the same data
    dataset: str | None = None
    # suspected-task moves (straggler monitor + worker engine-error
    # reports) — capped by max_task_retries so a job that
    # deterministically FAILS (worker survives, task never finishes)
    # can't re-dispatch forever
    retries: int = 0
    # every move (straggler + crash/transport) — capped by the much larger
    # max_task_moves so a job that deterministically KILLS its workers
    # (whose moves reset t_assigned and never look like stragglers) is
    # also bounded
    moves: int = 0

    @property
    def n_items(self) -> int:
        return self.end - self.start + 1

    def to_wire(self) -> dict[str, Any]:
        return {"model": self.model, "qnum": self.qnum, "worker": self.worker,
                "start": self.start, "end": self.end, "state": self.state,
                "t_assigned": self.t_assigned, "t_finished": self.t_finished,
                "dataset": self.dataset, "retries": self.retries,
                "moves": self.moves}

    @classmethod
    def from_wire(cls, d: dict[str, Any]) -> "Task":
        return cls(model=d["model"], qnum=int(d["qnum"]), worker=d["worker"],
                   start=int(d["start"]), end=int(d["end"]), state=d["state"],
                   t_assigned=float(d["t_assigned"]),
                   t_finished=float(d["t_finished"]),
                   dataset=d.get("dataset"),
                   retries=int(d.get("retries", 0)),
                   moves=int(d.get("moves", 0)))


class TaskBook:
    """All in-flight and finished tasks, indexed by query and by worker."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._by_query: dict[tuple[str, int], list[Task]] = {}

    # -- mutation ---------------------------------------------------------

    def record(self, tasks: list[Task]) -> None:
        with self._lock:
            for t in tasks:
                self._by_query.setdefault((t.model, t.qnum), []).append(t)

    def reassign(self, task: Task, new_worker: str, now: float,
                 count_retry: bool = False) -> Task:
        """Move an in-flight task to another worker (failure/straggler
        re-dispatch, `:706-760`). ``count_retry`` increments the
        retry-cap counter — set only for SUSPECTED-TASK moves (the
        straggler monitor and worker engine-error reports, both via
        `InferenceService._redispatch_or_fail`): moves caused by worker
        crashes or dispatch transport failures are infrastructure churn
        and must not consume the budget meant for jobs that
        deterministically fail wherever they run."""
        with self._lock:
            task.worker = new_worker
            task.t_assigned = now
            task.moves += 1
            if count_retry:
                task.retries += 1
            return task

    def assignment(self, task: Task) -> tuple[str, float, str]:
        """Atomic (worker, t_assigned, state) snapshot. Reading the two
        fields without the lock can tear against a concurrent `reassign`
        (new worker with the old stamp), which would stamp a JOB message
        no error report could ever match."""
        with self._lock:
            return task.worker, task.t_assigned, task.state

    def reassign_if_current(self, task: Task, expected_worker: str,
                            expected_stamp: float, new_worker: str,
                            now: float,
                            count_retry: bool = False) -> Task | None:
        """`reassign`, but only if the caller's view of the assignment is
        still the booked one. Dispatch retry loops run on several threads
        (member-change reassignment, straggler monitor, error reports) and
        share Task objects; a loop whose snapshot went stale must DROP its
        claim — the thread that re-booked the task owns its dispatch —
        instead of double-moving (and double-executing) it. Returns None
        when the book has moved on (also when the task finished/failed)."""
        with self._lock:
            if (task.state != WORKING or task.worker != expected_worker
                    or abs(task.t_assigned - expected_stamp) > 1e-6):
                return None
            return self.reassign(task, new_worker, now,
                                 count_retry=count_retry)

    def mark_failed(self, task: Task, now: float) -> Task:
        """Permanently fail a task (retry cap exhausted): the query will
        never be 'done'; `query_failed` surfaces it to pollers instead of
        letting them wait forever."""
        with self._lock:
            task.state = FAILED
            task.t_finished = now
            return task

    def mark_finished(self, model: str, qnum: int, start: int, end: int,
                      now: float) -> Task | None:
        """Flip the matching task to finished (`:645-652`); returns it, or
        None if no matching unfinished task (duplicate/stale result).
        A FAILED task also accepts: failure is a give-up marker, not a
        fact — a slow-but-correct worker delivering after the retry cap
        heals the query instead of having its records dropped."""
        with self._lock:
            for t in self._by_query.get((model, qnum), []):
                if t.start == start and t.end == end \
                        and t.state in (WORKING, FAILED):
                    t.state = FINISHED
                    t.t_finished = now
                    return t
        return None

    # -- queries ----------------------------------------------------------

    def tasks_for_query(self, model: str, qnum: int) -> list[Task]:
        with self._lock:
            return list(self._by_query.get((model, qnum), []))

    def query_done(self, model: str, qnum: int) -> bool:
        with self._lock:
            tasks = self._by_query.get((model, qnum), [])
            return bool(tasks) and all(t.state == FINISHED for t in tasks)

    def query_failed(self, model: str, qnum: int) -> bool:
        """True when any of the query's tasks is permanently failed."""
        with self._lock:
            return any(t.state == FAILED
                       for t in self._by_query.get((model, qnum), []))

    def tasks_on_worker(self, worker: str) -> list[Task]:
        """The reference's ``working_vm_set`` view (`:140-144`)."""
        with self._lock:
            return [t for ts in self._by_query.values() for t in ts
                    if t.worker == worker]

    def in_flight(self, worker: str | None = None) -> list[Task]:
        with self._lock:
            return [t for ts in self._by_query.values() for t in ts
                    if t.state == WORKING
                    and (worker is None or t.worker == worker)]

    def stragglers(self, now: float, timeout: float) -> list[Task]:
        """In-flight tasks assigned more than ``timeout`` ago — with the
        comparison the right way around (the reference computes
        ``start_time - time_now`` which is never positive, `:822`)."""
        with self._lock:
            return [t for ts in self._by_query.values() for t in ts
                    if t.state == WORKING and now - t.t_assigned > timeout]

    def queries(self) -> list[tuple[str, int]]:
        with self._lock:
            return sorted(self._by_query)

    # -- failover serialization ------------------------------------------

    def to_wire(self) -> list[dict[str, Any]]:
        with self._lock:
            return [t.to_wire() for ts in self._by_query.values() for t in ts]

    def load_wire(self, tasks: list[dict[str, Any]]) -> None:
        with self._lock:
            self._by_query.clear()
            for d in tasks:
                t = Task.from_wire(d)
                self._by_query.setdefault((t.model, t.qnum), []).append(t)
