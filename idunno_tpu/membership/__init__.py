from idunno_tpu.membership.service import MembershipService  # noqa: F401
from idunno_tpu.membership.list import MemberEntry, MembershipList  # noqa: F401
