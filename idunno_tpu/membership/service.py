"""Membership + failure detection service.

Reference semantics preserved (SURVEY.md C2):
- JOIN via introducer/master: newcomer asks the introducer, gets the full
  list back (`mp4_machinelearning.py:163-189`).
- Master-driven heartbeats: the acting master pings every other host on a
  0.3 s period, piggybacking its full membership list (`:191-220`);
  receivers merge by timestamp and PONG their own list back (`:272-287`).
- Suspicion: the acting master marks hosts LEAVE after 2 s of silence
  (`:832-884`) and the change propagates on the next ping wave.
- Voluntary leave (`:1038-1052`) is a LEAVE broadcast, distinct from a crash.

Beyond the reference (which hardcodes one master): mastership is *acting* —
if the configured coordinator is dead in the local view, the standby
coordinator assumes the heartbeat/monitor role, and it detects the
coordinator's death itself by ping silence. Status-change callbacks drive
store re-replication and scheduler reassignment (the reference couples these
inline in `monitor_program`, `:852-884`).

Periodic methods (``ping_once`` / ``monitor_once``) are pure steps driven by
the node runtime's threads — or directly by tests, no sleeping in here.
"""
from __future__ import annotations

import time
from collections.abc import Callable

from idunno_tpu.comm.message import Message
from idunno_tpu.comm.transport import Transport, TransportError
from idunno_tpu.config import ClusterConfig
from idunno_tpu.membership.epoch import (EpochFence, FenceRegistry,
                                         ScopeOwners, observe_payload)
from idunno_tpu.membership.health import HealthLedger, HealthPolicy
from idunno_tpu.membership.list import MembershipList
from idunno_tpu.utils.types import MemberStatus, MessageType

SERVICE = "membership"

# callback(host, old_status_or_None, new_status)
ChangeCallback = Callable[[str, MemberStatus | None, MemberStatus], None]


class MembershipService:
    def __init__(self, host: str, config: ClusterConfig, transport: Transport,
                 clock: Callable[[], float] = time.time) -> None:
        self.host = host
        self.config = config
        self.transport = transport
        self.clock = clock
        self.members = MembershipList()
        # coordinator epoch fence, shared by every service on this node
        # (stamped on coordinator verbs, advanced by gossip; epoch 0 /
        # no owner = bootstrap, the configured chain acts unfenced)
        self.epoch = EpochFence()
        # per-scope fences (one per managed LM pool/group, "pool:<name>");
        # scoped adoption mints here, scope views gossip beside the
        # cluster view — membership only ever OBSERVES scope stamps
        self.scopes = FenceRegistry()
        # gossiped scope→owner claims (routing only; the fences above are
        # the safety): pool-directed verbs go to the claimed owner first,
        # a wrong view costs one typed redirect hop
        self.owners = ScopeOwners()
        # differential fail-SLOW ledger (ISSUE 20): verdicts gossip on
        # every membership payload like scope views; the ledger never
        # forges a LEAVE — fail-stop detection below is untouched. It
        # only observes once a transport attaches it (node.py / chaos).
        self.health = HealthLedger(host, HealthPolicy.from_config(config),
                                   clock=clock)
        self._callbacks: list[ChangeCallback] = []
        self._left = False           # voluntary leave: never auto-refute
        transport.serve(SERVICE, self._handle)

    def _gossip_payload(self) -> dict:
        """The piggybacked view every membership message carries."""
        return {"members": self.members.to_wire(),
                "epoch": list(self.epoch.view()),
                "scopes": self.scopes.view_all(),
                "owners": self.owners.view_all(),
                "health": self.health.view_all()}

    # -- wiring -----------------------------------------------------------

    def on_change(self, cb: ChangeCallback, front: bool = False) -> None:
        """``front=True`` runs the callback before earlier registrations —
        the failover manager uses it so an adoption (epoch mint) lands
        before reassignment callbacks start dispatching under the old
        epoch."""
        if front:
            self._callbacks.insert(0, cb)
        else:
            self._callbacks.append(cb)

    def _fire(self, changes) -> None:
        for host, old, new in changes:
            for cb in self._callbacks:
                cb(host, old, new)

    # -- mastership -------------------------------------------------------

    def acting_master(self) -> str:
        """Where this node routes coordinator traffic: the current epoch
        owner while it is alive in the local view, else the configured
        coordinator→standby chain (the reference's primary→standby order,
        `mp4_machinelearning.py:47-48, 956-963` — but fence-aware: once an
        adoption minted an epoch, its owner stays master across heals
        instead of flapping back to the configured coordinator)."""
        _, owner = self.epoch.view()
        if owner is not None:
            o = self.members.get(owner)
            if o is None or o.status.alive:
                return owner
        c = self.config.coordinator
        if self.members.get(c) is None or self.members.is_alive(c):
            return c
        return self.config.standby_coordinator

    @property
    def is_acting_master(self) -> bool:
        """Acting-master DUTIES (dispatch, heartbeats, replication) require
        owning the fence: once any epoch has been minted, a node acts only
        if it is the owner — a node that merely *routes* to itself while a
        higher-epoch owner exists (e.g. the configured coordinator inside a
        partition that marked the owner LEAVE) stays fenced until it mints
        a higher epoch through FailoverManager.adopt."""
        if self.acting_master() != self.host:
            return False
        owner = self.epoch.owner()
        return owner is None or owner == self.host

    # -- lifecycle --------------------------------------------------------

    def join(self) -> None:
        """Introduce self. The introducer (or any alive seed) replies with
        the merged full list."""
        now = self.clock()
        self._left = False
        self.members.set(self.host, MemberStatus.RUNNING, now)
        self.members.touch(self.host, now)
        if self.host == self.config.introducer:
            return
        msg = Message(MessageType.JOIN, self.host, self._gossip_payload())
        for seed in (self.config.introducer, self.config.coordinator,
                     self.config.standby_coordinator):
            if seed == self.host:
                continue
            try:
                out = self.transport.call(seed, SERVICE, msg, timeout=5.0)
            except TransportError:
                continue
            if out is not None:
                # the ACK carries the cluster's fence view: a rejoiner that
                # lost its fence state re-learns the current epoch (and
                # every pool scope's) before it could ever act on a stale
                # one
                observe_payload(self.epoch, out.payload)
                self.scopes.observe_all(out.payload.get("scopes"))
                self.owners.observe_all(out.payload.get("owners"))
                self.health.observe_all(out.payload.get("health"))
                self._fire(self.members.merge(out.payload["members"]))
                return
        # nobody reachable — we are first up; keep our solo list.

    def leave(self) -> None:
        """Voluntary leave: broadcast a LEAVE-stamped list (distinct from a
        crash, which is only ever *detected*)."""
        now = self.clock()
        self._left = True
        self.members.set(self.host, MemberStatus.LEAVE, now)
        msg = Message(MessageType.LEAVE, self.host, self._gossip_payload())
        for h in self.config.hosts:
            if h != self.host:
                self.transport.datagram(  # lint: ok stamp -- _gossip_payload stamps the epoch view
                    h, SERVICE, msg)

    # -- periodic steps (driven by runtime threads or tests) --------------

    def ping_once(self) -> None:
        """Acting master only: heartbeat every other configured host with
        the full list piggybacked."""
        if not self.is_acting_master:
            return
        msg = Message(MessageType.PING, self.host, self._gossip_payload())
        for h in self.config.hosts:
            if h != self.host:
                self.transport.datagram(  # lint: ok stamp -- _gossip_payload stamps the epoch view
                    h, SERVICE, msg)

    def monitor_once(self) -> None:
        """Failure detection step.

        Acting master: mark alive members LEAVE after ``failure_timeout_s``
        of silence. Coordinator/standby when NOT acting master: watch only
        the current acting master's ping stream — silence there promotes
        the watcher on the next step (pre-fence this was standby-watches-
        coordinator only; with epochs the deposed coordinator equally
        watches the owner, so mastership can fail back under a NEW epoch
        when the owner dies).
        """
        now = self.clock()
        timeout = self.config.failure_timeout_s
        # differential health step (ISSUE 20): derive fail-slow verdicts
        # from what this node measured, then keep PROBING any peer under
        # a non-healthy verdict — quarantine diverts discretionary
        # traffic away from the peer, so recovery evidence must come
        # from somewhere, and a direct membership call (observed by the
        # transport's attached ledger) is that somewhere. Inert when no
        # transport ever attached the ledger (no samples -> no verdicts
        # -> no probes), so chaos schedules without the fail-slow flag
        # send not one extra datagram and existing seeds replay.
        self.health.tick(now)
        for peer in sorted(self.health.watched()):
            if peer == self.host or not self.members.is_alive(peer):
                continue
            try:
                self.transport.call(  # lint: ok stamp -- _gossip_payload stamps the epoch view
                    peer, SERVICE,
                    Message(MessageType.PING, self.host,
                            self._gossip_payload()),
                    timeout=max(0.5, self.config.ping_interval_s))
            except TransportError:
                pass  # observed as an error sample by the transport hook
        # SWIM-style refutation: if someone marked US dead (false suspicion
        # across a healed partition or a long GC pause) while we are in fact
        # alive, overwrite with a RUNNING stamp strictly newer than the
        # verdict's — max(now, verdict_ts + ε) wins the merge on every peer
        # even if our clock lags the issuer's (the ts domain doubles as the
        # incarnation number). Never after a voluntary leave.
        #
        # Convergence note: a healed node that was an isolated *coordinator*
        # may still carry LEAVE verdicts it issued for unreachable peers;
        # those propagate for one ping wave and each live peer refutes its
        # own entry on its next monitor tick, so views converge within
        # ~2 ping intervals (transient reassignment callbacks may fire —
        # exactly-once results hold regardless, see
        # tests/test_stress_concurrency.py). Genuinely dead peers stay dead.
        me = self.members.get(self.host)
        if me is not None and not me.status.alive and not self._left:
            refute_ts = max(now, me.ts + 1e-3)
            self.members.set(self.host, MemberStatus.RUNNING, refute_ts)
            # our own silence clocks are stale after an isolation — restart
            # them so we don't instantly re-suspect peers we couldn't hear
            for e in self.members.entries():
                self.members.touch(e.host, now)
            self._fire([(self.host, me.status, MemberStatus.RUNNING)])
        if self.is_acting_master:
            for e in self.members.entries():
                if e.host == self.host or not e.status.alive:
                    continue
                if not e.last_heard:
                    # never heard from (e.g. we just became master): start
                    # this host's silence clock NOW so a host that died
                    # during the failover window is still detected.
                    self.members.touch(e.host, now)
                    continue
                if now - e.last_heard > timeout:
                    self.members.set(e.host, MemberStatus.LEAVE, now)
                    self._fire([(e.host, MemberStatus.RUNNING,
                                 MemberStatus.LEAVE)])
        elif self.host in (self.config.coordinator,
                           self.config.standby_coordinator):
            target = self.acting_master()
            if target == self.host:
                return
            c = self.members.get(target)
            if (c is not None and c.status.alive and c.last_heard
                    and now - c.last_heard > timeout):
                self.members.set(c.host, MemberStatus.LEAVE, now)
                self._fire([(c.host, MemberStatus.RUNNING,
                             MemberStatus.LEAVE)])

    # -- message handling -------------------------------------------------

    def _handle(self, service: str, msg: Message) -> Message | None:
        now = self.clock()
        # fence gossip: every membership message carries the sender's
        # (epoch, owner) view; observing it here is what deposes a stale
        # coordinator within one ping wave of a heal. Scope views ride
        # beside it — membership observes scope fences, never rejects
        # (a deposed pool owner must still learn it was deposed)
        observe_payload(self.epoch, msg.payload)
        if isinstance(msg.payload, dict):
            self.scopes.observe_all(msg.payload.get("scopes"))
            self.owners.observe_all(msg.payload.get("owners"))
            # health verdicts gossip like scope views: observed, never
            # fenced — a quarantined peer must still learn its verdict
            self.health.observe_all(msg.payload.get("health"))
        if msg.type is MessageType.JOIN:
            self._fire(self.members.merge(msg.payload["members"]))
            self.members.touch(msg.sender, now)
            return Message(MessageType.ACK, self.host,
                           self._gossip_payload())
        if msg.type in (MessageType.PING, MessageType.PONG,
                        MessageType.LEAVE):
            self._fire(self.members.merge(msg.payload["members"]))
            self.members.touch(msg.sender, now)
            if msg.type is MessageType.PING:
                self.transport.datagram(
                    msg.sender, SERVICE,
                    Message(MessageType.PONG, self.host,
                            self._gossip_payload()))
            return None
        return None
