"""Membership list with merge-by-timestamp semantics.

Reference: each node keeps ``membership_list`` entries carrying a status and
a timestamp; on receiving a piggybacked list it keeps, per host, whichever
entry has the newer timestamp (`mp4_machinelearning.py:272-282`). A LEAVE
with a newer timestamp therefore overrides RUNNING and vice versa (rejoin).

``ts`` is the authoritative status-change time set by the owning/master node
(serialized); ``last_heard`` is a purely local monotonic receive time used by
the failure monitor (never serialized — the reference's separate
``last_update`` dict, `:847`).

All access is guarded by an internal lock: with the real socket transport,
merges arrive on the UDP receive thread concurrently with the heartbeat
thread iterating the list (the reference shares its dicts across 13 threads
with locks it never acquires — SURVEY.md §5).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any

from idunno_tpu.utils.types import MemberStatus


@dataclass
class MemberEntry:
    host: str
    status: MemberStatus
    ts: float                       # authoritative status-change time
    last_heard: float = 0.0         # local receive clock (not serialized)

    def to_wire(self) -> dict[str, Any]:
        return {"host": self.host, "status": self.status.value, "ts": self.ts}

    @classmethod
    def from_wire(cls, d: dict[str, Any]) -> "MemberEntry":
        return cls(host=d["host"], status=MemberStatus(d["status"]),
                   ts=float(d["ts"]))


class MembershipList:
    def __init__(self) -> None:
        self._entries: dict[str, MemberEntry] = {}
        self._lock = threading.RLock()

    def get(self, host: str) -> MemberEntry | None:
        with self._lock:
            return self._entries.get(host)

    def entries(self) -> list[MemberEntry]:
        """Snapshot, sorted by host."""
        with self._lock:
            return sorted(self._entries.values(), key=lambda e: e.host)

    def set(self, host: str, status: MemberStatus, ts: float) -> None:
        with self._lock:
            e = self._entries.get(host)
            if e is None:
                self._entries[host] = MemberEntry(host, status, ts)
            else:
                e.status, e.ts = status, ts

    def touch(self, host: str, now: float) -> None:
        with self._lock:
            e = self._entries.get(host)
            if e is not None:
                e.last_heard = max(e.last_heard, now)

    def alive_hosts(self) -> list[str]:
        return [e.host for e in self.entries() if e.status.alive]

    def is_alive(self, host: str) -> bool:
        e = self.get(host)
        return e is not None and e.status.alive

    def merge(self, wire_entries: list[dict[str, Any]]) -> list[tuple[str, MemberStatus | None, MemberStatus]]:
        """Merge a received list; returns status transitions
        [(host, old_status_or_None, new_status)] that resulted."""
        changes = []
        with self._lock:
            for d in wire_entries:
                incoming = MemberEntry.from_wire(d)
                mine = self._entries.get(incoming.host)
                if mine is None:
                    incoming.last_heard = 0.0
                    self._entries[incoming.host] = incoming
                    changes.append((incoming.host, None, incoming.status))
                elif incoming.ts > mine.ts:
                    old = mine.status
                    mine.status, mine.ts = incoming.status, incoming.ts
                    if old is not incoming.status:
                        changes.append((incoming.host, old, incoming.status))
        return changes

    def to_wire(self) -> list[dict[str, Any]]:
        return [e.to_wire() for e in self.entries()]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
