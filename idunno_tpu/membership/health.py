"""Differential health scoring: fail-SLOW detection beside fail-stop.

Membership (`membership/service.py`) detects only fail-stop — a limping
host that keeps its heartbeats keeps its traffic. This module closes the
differential-observability gap Huang et al. name in *Gray Failure: The
Achilles' Heel of Cloud-Scale Systems* (HotOS 2017): every node keeps
per-peer RPC service-latency EWMAs + error-rate EWMAs (fed by the
transport call sites in `comm/net.py` / `comm/inproc.py` and by the
manager's `lm_qos` gauge sweep), and a peer whose fleet-relative latency
deviation crosses policy while still heartbeat-alive walks a typed state
machine::

    healthy -> suspect --(breach sustained suspect_window_s)--> quarantined
                  |                                                |
                  +--(breach clears)--> healthy    (breach clears) v
       healthy <--(clean probation_s dwell)-- probation <----------+
                                                  |
                                                  +--(re-breach)--> quarantined

The ledger never forges a LEAVE — fail-stop detection is untouched; a
quarantined peer is still a cluster member, it just stops receiving
discretionary traffic (tenant-sticky decode routing, new scope claims,
full-window straggler patience) until probation clears it.

Verdicts gossip piggybacked on the five membership payloads under a
``"health"`` key, exactly like scope views: per-peer ``[state, seq,
score]`` where ``seq`` is a shared monotone bumped by whichever node
transitions the peer — merge keeps the higher seq (ties: more severe
state), so views converge like ``ScopeOwners`` claims. A node only
*derives* transitions for peers it holds >= ``min_samples`` local
observations on; sample-less nodes adopt gossip instead of "healing" a
quarantine they cannot see.

Injected clock throughout, zero rng — chaos seeds replay.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections.abc import Callable

HEALTHY = "healthy"
SUSPECT = "suspect"
QUARANTINED = "quarantined"
PROBATION = "probation"
STATES = (HEALTHY, SUSPECT, QUARANTINED, PROBATION)
# merge tiebreak at equal seq: more severe wins (deterministic everywhere)
_SEVERITY = {HEALTHY: 0, PROBATION: 1, SUSPECT: 2, QUARANTINED: 3}


@dataclasses.dataclass(frozen=True)
class HealthPolicy:
    """Thresholds for the differential detector (config: ``health_*``)."""

    ewma_alpha: float = 0.3
    # breach when ewma > deviation_factor * fleet-median ewma AND > floor
    # — the absolute floor keeps microsecond-noise fleets (and the chaos
    # harness's zero-latency baseline) from ever breaching on nothing
    deviation_factor: float = 3.0
    floor_s: float = 0.02
    min_samples: int = 5
    suspect_window_s: float = 1.0
    probation_s: float = 2.0
    error_rate: float = 0.5

    def __post_init__(self) -> None:
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError(f"ewma_alpha={self.ewma_alpha}")
        if self.deviation_factor <= 1.0:
            raise ValueError(f"deviation_factor={self.deviation_factor}")
        if self.min_samples < 1:
            raise ValueError(f"min_samples={self.min_samples}")
        for f in ("floor_s", "suspect_window_s", "probation_s"):
            if getattr(self, f) < 0:
                raise ValueError(f"{f}={getattr(self, f)}")
        if not 0.0 < self.error_rate <= 1.0:
            raise ValueError(f"error_rate={self.error_rate}")

    @classmethod
    def from_config(cls, config) -> "HealthPolicy":
        return cls(
            deviation_factor=config.health_deviation_factor,
            floor_s=config.health_floor_s,
            min_samples=config.health_min_samples,
            suspect_window_s=config.health_suspect_window_s,
            probation_s=config.health_probation_s,
            error_rate=config.health_error_rate)


class _Peer:
    """Per-peer observation + verdict record (all under the ledger lock)."""

    __slots__ = ("ewma", "n", "err", "serv_ewma", "serv_n",
                 "state", "seq", "t_breach", "t_clear")

    def __init__(self) -> None:
        self.ewma = 0.0        # RPC round-trip latency EWMA (s)
        self.n = 0             # RPC samples seen
        self.err = 0.0         # error-rate EWMA (1.0 = every call fails)
        self.serv_ewma = 0.0   # service-level latency EWMA (qos p95, s)
        self.serv_n = 0
        self.state = HEALTHY
        self.seq = 0
        self.t_breach = 0.0    # when the current breach streak started
        self.t_clear = 0.0     # when probation started


class HealthLedger:
    """One per node; owned by ``MembershipService`` as ``.health``."""

    def __init__(self, host: str, policy: HealthPolicy | None = None,
                 clock: Callable[[], float] = time.time) -> None:
        self.host = host
        self.policy = policy or HealthPolicy()
        self.clock = clock
        self._lock = threading.RLock()
        self._peers: dict[str, _Peer] = {}
        self._remote: dict[str, float] = {}   # gossiped scores, display only
        # True once any direct observation landed: gates the gauge-sweep
        # feed so a cluster whose transports never attached the ledger
        # (chaos default schedules) derives nothing and shifts no seed
        self.active = False

    # -- observation feeds -------------------------------------------------

    def observe(self, peer: str, latency_s: float,
                error: bool = False) -> None:
        """One RPC round-trip against ``peer`` (transport call sites)."""
        if peer == self.host:
            return
        a = self.policy.ewma_alpha
        with self._lock:
            self.active = True
            p = self._peers.setdefault(peer, _Peer())
            lat = max(0.0, float(latency_s))
            p.ewma = lat if p.n == 0 else (1 - a) * p.ewma + a * lat
            p.err = (1 - a) * p.err + a * (1.0 if error else 0.0)
            p.n += 1

    def observe_service(self, peer: str, seconds: float) -> None:
        """Service-level latency signal (the manager's lm_qos p95 sweep).

        Ignored until the ledger is ``active`` (some transport observed a
        real call): a ledger nobody wired to a transport must stay inert.
        """
        if peer == self.host or not self.active or seconds <= 0.0:
            return
        a = self.policy.ewma_alpha
        with self._lock:
            p = self._peers.setdefault(peer, _Peer())
            s = float(seconds)
            p.serv_ewma = s if p.serv_n == 0 else \
                (1 - a) * p.serv_ewma + a * s
            p.serv_n += 1

    # -- verdict derivation ------------------------------------------------

    def _median(self, vals: list[float]) -> float:
        if not vals:
            return 0.0
        vs = sorted(vals)
        m = len(vs) // 2
        return vs[m] if len(vs) % 2 else 0.5 * (vs[m - 1] + vs[m])

    def _breach_locked(self, host: str, p: _Peer,
                       rpc: list[tuple[str, float]],
                       serv: list[tuple[str, float]]) -> bool:
        """Fleet-relative deviation with a LEAVE-ONE-OUT median: ``host``
        is judged against the median of the OTHER measured peers, never
        against a baseline it dominates. A ledger that mostly talks to
        one peer (a pool owner forwarding to its one replica node) would
        otherwise use the limping peer's own EWMA as "the fleet" and
        derive no breach — then fight every other ledger's quarantine
        verdict with probation heals, seq-bumping forever. With no other
        measured peer the median is 0 and the absolute floor governs."""
        pol = self.policy
        if p.n >= pol.min_samples:
            med = self._median([e for h, e in rpc if h != host])
            if p.ewma > max(pol.floor_s, pol.deviation_factor * med):
                return True
            if p.err > pol.error_rate:
                return True
        if p.serv_n >= pol.min_samples:
            med = self._median([e for h, e in serv if h != host])
            if p.serv_ewma > max(pol.floor_s,
                                 pol.deviation_factor * med):
                return True
        return False

    def tick(self, now: float | None = None) -> list[tuple[str, str, str]]:
        """Advance the state machine from local observations. Returns the
        transitions fired as ``(peer, old_state, new_state)``."""
        if now is None:
            now = self.clock()
        pol = self.policy
        out: list[tuple[str, str, str]] = []
        with self._lock:
            rpc = [(h, p.ewma) for h, p in self._peers.items()
                   if p.n >= pol.min_samples]
            serv = [(h, p.serv_ewma) for h, p in self._peers.items()
                    if p.serv_n >= pol.min_samples]
            for host, p in self._peers.items():
                # no local evidence -> the gossiped verdict stands
                if p.n < pol.min_samples and p.serv_n < pol.min_samples:
                    continue
                breach = self._breach_locked(host, p, rpc, serv)
                old = p.state
                if p.state == HEALTHY and breach:
                    p.state, p.t_breach = SUSPECT, now
                elif p.state == SUSPECT:
                    if not breach:
                        p.state = HEALTHY
                    elif now - p.t_breach >= pol.suspect_window_s:
                        p.state = QUARANTINED
                elif p.state == QUARANTINED and not breach:
                    p.state, p.t_clear = PROBATION, now
                elif p.state == PROBATION:
                    if breach:
                        p.state = QUARANTINED
                    elif now - p.t_clear >= pol.probation_s:
                        p.state = HEALTHY
                if p.state != old:
                    p.seq += 1
                    out.append((host, old, p.state))
        return out

    # -- gossip ------------------------------------------------------------

    def view_all(self) -> dict[str, list]:
        """Wire form: {peer: [state, seq, score_ms]} for non-trivial rows
        (a healthy seq-0 peer carries no information)."""
        with self._lock:
            return {h: [p.state, p.seq, round(p.ewma, 6)]
                    for h, p in self._peers.items()
                    if p.seq > 0 or p.state != HEALTHY}

    def observe_all(self, views: dict | None) -> None:
        """Merge a gossiped view: higher seq wins, ties go to the more
        severe state — same last-writer-wins register shape as
        ``ScopeOwners``, so every node converges on one verdict."""
        if not views:
            return
        with self._lock:
            for host, rec in views.items():
                if host == self.host:
                    continue
                try:
                    state, seq, score = rec[0], int(rec[1]), float(rec[2])
                except (TypeError, ValueError, IndexError):
                    continue
                if state not in _SEVERITY:
                    continue
                p = self._peers.setdefault(host, _Peer())
                if seq > p.seq or (seq == p.seq and
                                   _SEVERITY[state] > _SEVERITY[p.state]):
                    # adopting a fresher verdict restarts the local
                    # windows so our own next tick measures from now
                    if state == SUSPECT and p.state != SUSPECT:
                        p.t_breach = self.clock()
                    if state == PROBATION and p.state != PROBATION:
                        p.t_clear = self.clock()
                    p.state, p.seq = state, seq
                    self._remote[host] = score

    # -- accessors ---------------------------------------------------------

    def state(self, peer: str) -> str:
        with self._lock:
            p = self._peers.get(peer)
            return p.state if p is not None else HEALTHY

    def score(self, peer: str) -> float:
        with self._lock:
            p = self._peers.get(peer)
            if p is None:
                return 0.0
            return p.ewma if p.n else self._remote.get(peer, 0.0)

    def quarantined(self) -> set[str]:
        with self._lock:
            return {h for h, p in self._peers.items()
                    if p.state == QUARANTINED}

    def unhealthy(self) -> set[str]:
        """Peers under suspicion or worse (early-redispatch consumers)."""
        with self._lock:
            return {h for h, p in self._peers.items()
                    if p.state in (SUSPECT, QUARANTINED)}

    def watched(self) -> set[str]:
        """Peers in any non-healthy state: membership keeps probing these
        directly so recovery evidence arrives even after routing stopped
        sending them discretionary traffic."""
        with self._lock:
            return {h for h, p in self._peers.items()
                    if p.state != HEALTHY}

    def worst_ratio(self) -> float:
        """Max fleet-relative latency deviation (1.0 = at the median);
        the ``node_health_score`` gauge."""
        pol = self.policy
        with self._lock:
            med = self._median([p.ewma for p in self._peers.values()
                                if p.n >= pol.min_samples])
            base = max(pol.floor_s, med)
            ratios = [p.ewma / base for p in self._peers.values()
                      if p.n >= pol.min_samples]
            return max(ratios) if ratios else 0.0

    def gauges(self) -> dict:
        return {"node_health_score": round(self.worst_ratio(), 4),
                "quarantined_nodes": len(self.quarantined())}

    def table(self) -> list[tuple[str, str, float]]:
        """(peer, state, score) rows for the shell's list-master view."""
        with self._lock:
            return sorted(
                (h, p.state, round(p.ewma if p.n
                                   else self._remote.get(h, 0.0), 6))
                for h, p in self._peers.items())
