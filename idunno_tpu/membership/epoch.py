"""Monotone epoch fence for coordinator actions (Raft-style terms).

The reference's client failover (`mp4_machinelearning.py:956-963`) retries
primary→standby with no fencing: after a partition isolates the primary,
both coordinators keep dispatching and nothing deposes the stale one when
the network heals (SURVEY.md §7 bug-not-to-replicate). Here every adoption
mints a strictly increasing epoch (Ongaro & Ousterhout, "In Search of an
Understandable Consensus Algorithm", 2014 — the term mechanism only; no
log replication or quorum election, the standby chain is configured).
Coordinator-originated verbs (dispatch, metadata replication, lm_* control
RPCs, SDFS internal pushes) are stamped with the sender's fence view;
every receiver tracks the highest epoch seen, rejects lower-epoch verbs
with a typed ``StaleEpoch`` reply, and a deposed coordinator that observes
a higher epoch steps down — split brain becomes impossible by
construction, and heal-time convergence is automatic because the fence
view also rides the membership ping/pong gossip.

Epoch 0 with no owner is the bootstrap state: the configured coordinator
acts without minting, so a cluster that never fails over never pays for
fencing (and older snapshots without an ``epoch`` field load unchanged).
"""
from __future__ import annotations

import threading

from idunno_tpu.comm.message import Message
from idunno_tpu.comm.transport import TransportError
from idunno_tpu.utils.types import MessageType


class StaleEpoch(TransportError):
    """A peer rejected our verb because it has seen a higher epoch — we are
    (or are acting for) a deposed coordinator. Never retryable: retrying a
    fenced verb cannot succeed, the caller must step down instead."""

    def __init__(self, message: str, epoch: int = 0,
                 owner: str | None = None) -> None:
        super().__init__(message, reason="stale_epoch")
        self.epoch = epoch
        self.owner = owner


class EpochFence:
    """Thread-safe (epoch, owner) high-water mark.

    ``observe`` advances on gossip/stamps from peers; ``mint`` is called by
    an adopting coordinator and returns a strictly higher epoch owned by
    it. On equal epochs the first-seen owner is kept (two mints of the
    same epoch cannot happen through ``adopt`` because the snapshot carries
    the old epoch and ``mint`` goes strictly above the high-water)."""

    def __init__(self) -> None:
        self._epoch = 0
        self._owner: str | None = None
        self._lock = threading.Lock()

    def current(self) -> int:
        with self._lock:
            return self._epoch

    def owner(self) -> str | None:
        with self._lock:
            return self._owner

    def view(self) -> tuple[int, str | None]:
        with self._lock:
            return self._epoch, self._owner

    def observe(self, epoch: int, owner: str | None = None) -> bool:
        """Advance the high-water mark; True if it moved."""
        with self._lock:
            if epoch > self._epoch:
                self._epoch = int(epoch)
                self._owner = owner
                return True
            return False

    def mint(self, owner: str) -> int:
        with self._lock:
            self._epoch += 1
            self._owner = owner
            return self._epoch


# -- wire helpers (shared by every stamped service) ------------------------

def stamp(fence: EpochFence, payload: dict) -> dict:
    """Stamp a coordinator-originated payload with the sender's fence view
    (in place; returns the payload for chaining)."""
    e, owner = fence.view()
    payload["epoch"] = [e, owner]
    return payload


def observe_payload(fence: EpochFence, payload) -> None:
    """Advance the local fence from a stamped payload without rejecting —
    for peer-originated messages (worker results, gossip) whose work is
    valid at any epoch."""
    ep = payload.get("epoch") if isinstance(payload, dict) else None
    if ep:
        fence.observe(int(ep[0]), ep[1])


def check_payload(fence: EpochFence, payload, host: str) -> Message | None:
    """Receiver-side fence check for a coordinator-originated verb: returns
    a typed stale-epoch ERROR reply if the stamp is below the local
    high-water mark, else observes the stamp and returns None. Unstamped
    payloads (client RPCs, pre-fence peers) always pass."""
    ep = payload.get("epoch") if isinstance(payload, dict) else None
    if not ep:
        return None
    e = int(ep[0])
    cur, owner = fence.view()
    if e < cur:
        return Message(MessageType.ERROR, host,
                       {"error": f"stale epoch {e} < {cur}"
                                 f" (owner {owner})",
                        "stale_epoch": True, "epoch": [cur, owner]})
    fence.observe(e, ep[1])
    return None


def reply_is_stale(fence: EpochFence, reply: Message | None) -> bool:
    """Sender-side: True if the reply is a stale-epoch rejection. Observes
    the rejecting peer's (higher) fence view so the caller demotes."""
    if reply is None or reply.type is not MessageType.ERROR:
        return False
    p = reply.payload if isinstance(reply.payload, dict) else {}
    if not p.get("stale_epoch"):
        return False
    observe_payload(fence, p)
    return True
