"""Monotone epoch fence for coordinator actions (Raft-style terms).

The reference's client failover (`mp4_machinelearning.py:956-963`) retries
primary→standby with no fencing: after a partition isolates the primary,
both coordinators keep dispatching and nothing deposes the stale one when
the network heals (SURVEY.md §7 bug-not-to-replicate). Here every adoption
mints a strictly increasing epoch (Ongaro & Ousterhout, "In Search of an
Understandable Consensus Algorithm", 2014 — the term mechanism only; no
log replication or quorum election, the standby chain is configured).
Coordinator-originated verbs (dispatch, metadata replication, lm_* control
RPCs, SDFS internal pushes) are stamped with the sender's fence view;
every receiver tracks the highest epoch seen, rejects lower-epoch verbs
with a typed ``StaleEpoch`` reply, and a deposed coordinator that observes
a higher epoch steps down — split brain becomes impossible by
construction, and heal-time convergence is automatic because the fence
view also rides the membership ping/pong gossip.

Epoch 0 with no owner is the bootstrap state: the configured coordinator
acts without minting, so a cluster that never fails over never pays for
fencing (and older snapshots without an ``epoch`` field load unchanged).
"""
from __future__ import annotations

import threading

from idunno_tpu.comm.message import Message
from idunno_tpu.comm.transport import TransportError
from idunno_tpu.utils.types import MessageType


class StaleEpoch(TransportError):
    """A peer rejected our verb because it has seen a higher epoch — we are
    (or are acting for) a deposed coordinator. Never retryable: retrying a
    fenced verb cannot succeed, the caller must step down instead."""

    def __init__(self, message: str, epoch: int = 0,
                 owner: str | None = None) -> None:
        super().__init__(message, reason="stale_epoch")
        self.epoch = epoch
        self.owner = owner


class StaleScope(TransportError):
    """A peer rejected our verb because it has seen a higher epoch for ONE
    fence scope (a managed pool/group) — only that scope's journal is
    fenced; the cluster-wide fence and every other scope are untouched.
    Never retryable for the same reason as StaleEpoch, but the caller
    steps down for the named scope only."""

    def __init__(self, message: str, scope: str, epoch: int = 0,
                 owner: str | None = None) -> None:
        super().__init__(message, reason="stale_scope")
        self.scope = scope
        self.epoch = epoch
        self.owner = owner


class EpochFence:
    """Thread-safe (epoch, owner) high-water mark.

    ``observe`` advances on gossip/stamps from peers; ``mint`` is called by
    an adopting coordinator and returns a strictly higher epoch owned by
    it. On equal epochs the first-seen owner is kept (two mints of the
    same epoch cannot happen through ``adopt`` because the snapshot carries
    the old epoch and ``mint`` goes strictly above the high-water)."""

    def __init__(self) -> None:
        self._epoch = 0
        self._owner: str | None = None
        self._lock = threading.Lock()

    def current(self) -> int:
        with self._lock:
            return self._epoch

    def owner(self) -> str | None:
        with self._lock:
            return self._owner

    def view(self) -> tuple[int, str | None]:
        with self._lock:
            return self._epoch, self._owner

    def observe(self, epoch: int, owner: str | None = None) -> bool:
        """Advance the high-water mark; True if it moved."""
        with self._lock:
            if epoch > self._epoch:
                self._epoch = int(epoch)
                self._owner = owner
                return True
            return False

    def mint(self, owner: str) -> int:
        with self._lock:
            self._epoch += 1
            self._owner = owner
            return self._epoch


class FenceRegistry:
    """Keyed fence map: one ``EpochFence`` per scope (``pool:<name>`` for
    managed LM pools/replica groups), created on demand. Each scope's
    epoch advances independently, so adopting one pool's fence deposes the
    old owner for THAT pool only — the cluster-wide ``EpochFence`` remains
    the authority for membership + SDFS-master duties. Scope views ride
    the membership gossip (``"scopes"`` payload key) exactly like the
    cluster fence view rides ``"epoch"``."""

    def __init__(self) -> None:
        self._fences: dict[str, EpochFence] = {}
        self._lock = threading.Lock()

    def fence(self, scope: str) -> EpochFence:
        with self._lock:
            f = self._fences.get(scope)
            if f is None:
                f = self._fences[scope] = EpochFence()
            return f

    def scopes(self) -> list[str]:
        with self._lock:
            return sorted(self._fences)

    def view_all(self) -> dict[str, list]:
        """Gossip wire form: only scopes that ever moved off bootstrap
        (a never-minted scope carries no fencing information)."""
        with self._lock:
            fences = dict(self._fences)
        out: dict[str, list] = {}
        for scope, f in fences.items():
            e, owner = f.view()
            if e > 0 or owner is not None:
                out[scope] = [e, owner]
        return out

    def observe_all(self, views) -> None:
        if not isinstance(views, dict):
            return
        for scope, ep in views.items():
            if ep:
                self.fence(str(scope)).observe(int(ep[0]), ep[1])


def pool_scope(name: str) -> str:
    """Fence scope for a managed pool name. Replica-group members
    (``{group}@r{i}``) share their group's scope: the group journal +
    scale WAL are one ownership unit, so its replicas fence together."""
    return f"pool:{name.rsplit('@r', 1)[0]}"


def place_scope(scope: str, hosts, alive, quarantined=()) -> str | None:
    """Deterministic owner for a pool scope: the first ALIVE host in the
    scope's rendezvous order over the full configured registry
    (utils/ring.py:rendezvous_order). Every node computes the same
    answer from the same membership view, and one host's death moves
    only the scopes that ranked it first. None when nothing is alive.

    ``quarantined`` (gray-failure defense, membership/health.py): hosts
    the health ledger has quarantined are skipped when minting NEW
    owners — a limping host must not win placement — unless skipping
    them would leave nothing (availability beats health)."""
    from idunno_tpu.utils.ring import rendezvous_order
    alive = set(alive)
    quarantined = set(quarantined)
    fallback = None
    for h in rendezvous_order(scope, tuple(hosts)):
        if h not in alive:
            continue
        if h in quarantined:
            if fallback is None:
                fallback = h
            continue
        return h
    return fallback


class ScopeOwnerRedirect(Exception):
    """A pool-directed verb landed on a host that is not the scope's
    placed owner — the typed one-hop redirect: the ERROR reply names the
    owner so the client re-sends there directly (one hop, counted as
    ``scope_owner_redirects``), instead of walking the coordinator
    chain."""

    def __init__(self, scope: str, owner: str | None) -> None:
        super().__init__(f"scope {scope} is owned by {owner}; redirect")
        self.scope = scope
        self.owner = owner


class ScopeOwners:
    """Gossiped scope→owner claim map, the routing half of multi-owner
    placement (the fences in ``FenceRegistry`` are the safety half).
    Each claim carries a per-scope monotone seq; ``observe_all`` keeps
    the higher seq and breaks exact ties on the lexicographically
    greater owner so every node converges to the same view without
    coordination. Claims are advisory routing state — a wrong view
    costs one redirect hop or a scoped fence check, never
    correctness."""

    def __init__(self) -> None:
        self._map: dict[str, tuple[str, int]] = {}
        self._lock = threading.Lock()

    def owner(self, scope: str) -> str | None:
        with self._lock:
            ent = self._map.get(scope)
            return ent[0] if ent else None

    def view(self, scope: str) -> tuple[str, int] | None:
        with self._lock:
            ent = self._map.get(scope)
            return (ent[0], ent[1]) if ent else None

    def scopes(self) -> list[str]:
        with self._lock:
            return sorted(self._map)

    def owned_by(self, host: str) -> list[str]:
        with self._lock:
            return sorted(s for s, (o, _) in self._map.items()
                          if o == host)

    def claim(self, scope: str, owner: str) -> int:
        """Record ``owner`` as the scope's owner at a seq strictly above
        everything observed — a claim always wins over the state it was
        made from, and replicated/gossiped copies of an OLD claim can
        never re-demote it."""
        with self._lock:
            ent = self._map.get(scope)
            seq = (ent[1] if ent else 0) + 1
            self._map[scope] = (owner, seq)
            return seq

    def view_all(self) -> dict[str, list]:
        """Gossip wire form: ``{scope: [owner, seq]}``."""
        with self._lock:
            return {s: [o, q] for s, (o, q) in self._map.items()}

    def observe_all(self, views) -> None:
        if not isinstance(views, dict):
            return
        with self._lock:
            for scope, ent in views.items():
                if not ent:
                    continue
                owner, seq = str(ent[0]), int(ent[1])
                cur = self._map.get(str(scope))
                if (cur is None or seq > cur[1]
                        or (seq == cur[1] and owner > cur[0])):
                    self._map[str(scope)] = (owner, seq)


# -- wire helpers (shared by every stamped service) ------------------------

def stamp(fence: EpochFence, payload: dict) -> dict:
    """Stamp a coordinator-originated payload with the sender's fence view
    (in place; returns the payload for chaining)."""
    e, owner = fence.view()
    payload["epoch"] = [e, owner]
    return payload


def observe_payload(fence: EpochFence, payload) -> None:
    """Advance the local fence from a stamped payload without rejecting —
    for peer-originated messages (worker results, gossip) whose work is
    valid at any epoch."""
    ep = payload.get("epoch") if isinstance(payload, dict) else None
    if ep:
        fence.observe(int(ep[0]), ep[1])


def check_payload(fence: EpochFence, payload, host: str) -> Message | None:
    """Receiver-side fence check for a coordinator-originated verb: returns
    a typed stale-epoch ERROR reply if the stamp is below the local
    high-water mark, else observes the stamp and returns None. Unstamped
    payloads (client RPCs, pre-fence peers) always pass."""
    ep = payload.get("epoch") if isinstance(payload, dict) else None
    if not ep:
        return None
    e = int(ep[0])
    cur, owner = fence.view()
    if e < cur:
        return Message(MessageType.ERROR, host,
                       {"error": f"stale epoch {e} < {cur}"
                                 f" (owner {owner})",
                        "stale_epoch": True, "epoch": [cur, owner]})
    fence.observe(e, ep[1])
    return None


def reply_is_stale(fence: EpochFence, reply: Message | None) -> bool:
    """Sender-side: True if the reply is a stale-epoch rejection. Observes
    the rejecting peer's (higher) fence view so the caller demotes."""
    if reply is None or reply.type is not MessageType.ERROR:
        return False
    p = reply.payload if isinstance(reply.payload, dict) else {}
    if not p.get("stale_epoch"):
        return False
    observe_payload(fence, p)
    return True


# -- scoped wire helpers (per-pool fences) ---------------------------------
#
# Scoped stamps ride BESIDE the cluster stamp under their own payload key
# ("scope_epoch": [scope, e, owner]) and scoped rejections use "stale_scope"
# — never "stale_epoch" — so a pool-level deposal can NOT demote the sender
# cluster-wide through reply_is_stale. Unstamped payloads pass everywhere,
# exactly like the cluster fence.

def stamp_scoped(registry: FenceRegistry, scope: str,
                 payload: dict) -> dict:
    """Stamp a pool-directed payload with the sender's scope-fence view
    (in place; returns the payload for chaining)."""
    e, owner = registry.fence(scope).view()
    payload["scope_epoch"] = [scope, e, owner]
    return payload


def observe_scoped(registry: FenceRegistry, payload) -> None:
    """Advance the local scope fence from a stamped payload without
    rejecting (gossip / replies)."""
    ep = payload.get("scope_epoch") if isinstance(payload, dict) else None
    if ep:
        registry.fence(str(ep[0])).observe(int(ep[1]), ep[2])


def check_scoped(registry: FenceRegistry, payload,
                 host: str) -> Message | None:
    """Receiver-side scope-fence check: a stamp below the local high-water
    mark for its scope gets a typed stale-scope ERROR reply (the rejection
    names the scope and carries the rejecting view); else the stamp is
    observed and None returned. Unstamped payloads always pass."""
    ep = payload.get("scope_epoch") if isinstance(payload, dict) else None
    if not ep:
        return None
    scope, e = str(ep[0]), int(ep[1])
    fence = registry.fence(scope)
    cur, owner = fence.view()
    if e < cur:
        return Message(MessageType.ERROR, host,
                       {"error": f"stale scope epoch {e} < {cur} for "
                                 f"{scope} (owner {owner}): the managed "
                                 "journal for this scope is fenced",
                        "stale_scope": scope,
                        "scope_epoch": [scope, cur, owner]})
    fence.observe(e, ep[2])
    return None


def reply_stale_scope(registry: FenceRegistry,
                      reply: Message | None) -> str | None:
    """Sender-side: the fenced scope name if the reply is a stale-scope
    rejection (observing the rejecting peer's view), else None."""
    if reply is None or reply.type is not MessageType.ERROR:
        return None
    p = reply.payload if isinstance(reply.payload, dict) else {}
    scope = p.get("stale_scope")
    if not scope:
        return None
    observe_scoped(registry, p)
    return str(scope)
