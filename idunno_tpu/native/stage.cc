// Native host-side image staging (the data-loader hot path).
//
// The reference's host pipeline is a per-image Python loop with PIL
// transforms (`alexnet_resnet.py:46-66`). The TPU engine consumes canonical
// uint8 [N, S, S, 3] batches; producing them from decoded frames is pure
// memory-bandwidth + interpolation work that belongs in native code:
//   - resize_bilinear_u8: decoded RGB frame -> target size (OpenMP across
//     rows, auto-vectorized inner loop; fixed-point weights)
//   - stage_batch_u8: K decoded frames -> one contiguous batch buffer with
//     shortest-side-resize + center-crop semantics (OpenMP across frames)
//
// Built on demand with `g++ -O3 -march=native -fopenmp -shared -fPIC` by
// idunno_tpu.native (ctypes binding, graceful numpy fallback).

#include <cstdint>
#include <cstring>
#include <algorithm>

extern "C" {

// Bilinear resize of an interleaved RGB u8 image. Fixed-point (16.16),
// half-pixel convention: sx = (x + 0.5) * sw/dw - 0.5, clamped. The numpy
// fallback in idunno_tpu/native/__init__.py implements the exact same
// fixed-point math so native and fallback staging are pixel-identical
// (cross-host determinism does not depend on the toolchain being present).
static inline int64_t clamp64(int64_t v, int64_t lo, int64_t hi) {
    return v < lo ? lo : (v > hi ? hi : v);
}

void resize_bilinear_u8(const uint8_t* src, int sh, int sw,
                        uint8_t* dst, int dh, int dw) {
    const int64_t x_step = ((int64_t)sw << 16) / dw;
    const int64_t y_step = ((int64_t)sh << 16) / dh;
#pragma omp parallel for schedule(static)
    for (int y = 0; y < dh; ++y) {
        const int64_t sy = clamp64(
            y * y_step + y_step / 2 - (1LL << 15), 0, (int64_t)(sh - 1) << 16);
        const int y0 = (int)(sy >> 16);
        const int y1 = std::min(y0 + 1, sh - 1);
        const int fy = (int)(sy & 0xffff);
        const uint8_t* row0 = src + (int64_t)y0 * sw * 3;
        const uint8_t* row1 = src + (int64_t)y1 * sw * 3;
        uint8_t* out = dst + (int64_t)y * dw * 3;
        for (int x = 0; x < dw; ++x) {
            const int64_t sx = clamp64(
                x * x_step + x_step / 2 - (1LL << 15), 0,
                (int64_t)(sw - 1) << 16);
            const int x0 = (int)(sx >> 16);
            const int x1 = std::min(x0 + 1, sw - 1);
            const int fx = (int)(sx & 0xffff);
            for (int c = 0; c < 3; ++c) {
                const int p00 = row0[x0 * 3 + c], p01 = row0[x1 * 3 + c];
                const int p10 = row1[x0 * 3 + c], p11 = row1[x1 * 3 + c];
                const int64_t top = ((int64_t)p00 << 16)
                                    + (int64_t)(p01 - p00) * fx;
                const int64_t bot = ((int64_t)p10 << 16)
                                    + (int64_t)(p11 - p10) * fx;
                const int64_t val = (top << 16) + (bot - top) * (int64_t)fy;
                out[x * 3 + c] = (uint8_t)((val + (1LL << 31)) >> 32);
            }
        }
    }
}

// Stage K independently-sized decoded frames into one contiguous
// [k, size, size, 3] batch: shortest-side resize to `size`, center crop.
// frames: array of k pointers; dims: [k][2] = (h, w) per frame.
void stage_batch_u8(const uint8_t* const* frames, const int32_t* dims,
                    int k, int size, uint8_t* dst) {
#pragma omp parallel for schedule(dynamic)
    for (int i = 0; i < k; ++i) {
        const int sh = dims[i * 2], sw = dims[i * 2 + 1];
        // shortest-side target dims, rounded division (matches the
        // fallback's (d * size + s / 2) / s exactly)
        int rh, rw;
        if (sw <= sh) {
            rw = size;
            rh = std::max((int64_t)size,
                          ((int64_t)sh * size + sw / 2) / sw);
        } else {
            rh = size;
            rw = std::max((int64_t)size,
                          ((int64_t)sw * size + sh / 2) / sh);
        }
        uint8_t* tmp = new uint8_t[(int64_t)rh * rw * 3];
        resize_bilinear_u8(frames[i], sh, sw, tmp, rh, rw);
        const int top = (rh - size) / 2, left = (rw - size) / 2;
        uint8_t* out = dst + (int64_t)i * size * size * 3;
        for (int y = 0; y < size; ++y) {
            std::memcpy(out + (int64_t)y * size * 3,
                        tmp + ((int64_t)(y + top) * rw + left) * 3,
                        (size_t)size * 3);
        }
        delete[] tmp;
    }
}

}  // extern "C"
