// Native log-scan engine for the distributed grep service.
//
// The reference's grep subsystem (mp1_client/mp1_server, imported at
// `mp4_machinelearning.py:15-16` but missing from the repo) scanned VM logs
// in Python. Serving-cluster logs run to the rotating-file cap (100 MB,
// `mp4_machinelearning.py:62-74`); scanning them line-by-line in Python is
// ~100x slower than memory bandwidth. This scanner mmaps the file and
// OpenMP-splits it into newline-aligned chunks; each thread memmem-scans
// its chunk for a literal needle and records matching line-start offsets.
// Regex patterns stay on the Python fallback path (idunno_tpu.grep).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// Scan `path` for lines containing the literal `needle`.
// Returns total matching-line count, or -1 on I/O error. Writes up to `cap`
// matching line-start offsets (ascending) and the number written.
int64_t grep_literal(const char* path, const char* needle,
                     int64_t* offsets, int64_t cap, int64_t* n_written) {
    *n_written = 0;
    const int fd = open(path, O_RDONLY);
    if (fd < 0) return -1;
    struct stat st {};
    if (fstat(fd, &st) != 0) {
        close(fd);
        return -1;
    }
    if (st.st_size == 0) {
        close(fd);
        return 0;
    }
    const size_t size = (size_t)st.st_size;
    const char* data =
        (const char*)mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
    close(fd);
    if (data == MAP_FAILED) return -1;

    const size_t nlen = strlen(needle);
    int n_chunks = 1;
#ifdef _OPENMP
    n_chunks = (int)std::min<size_t>(16, std::max<size_t>(1, size >> 22));
#endif
    std::vector<int64_t> counts(n_chunks, 0);
    std::vector<std::vector<int64_t>> hits(n_chunks);

#pragma omp parallel for schedule(static)
    for (int c = 0; c < n_chunks; ++c) {
        // chunk c owns lines whose first byte lies in [lo, hi)
        size_t lo = size * c / n_chunks, hi = size * (c + 1) / n_chunks;
        if (c > 0) {   // advance to the first line START inside the chunk
            const char* nl = (const char*)memchr(data + lo - 1, '\n',
                                                 size - lo + 1);
            lo = nl ? (size_t)(nl - data) + 1 : size;
        }
        size_t pos = lo;
        while (pos < hi) {
            const char* nl = (const char*)memchr(data + pos, '\n',
                                                 size - pos);
            const size_t line_end = nl ? (size_t)(nl - data) : size;
            if (nlen == 0 ||
                memmem(data + pos, line_end - pos, needle, nlen)) {
                ++counts[c];
                hits[c].push_back((int64_t)pos);
            }
            pos = line_end + 1;
        }
    }

    munmap((void*)data, size);
    int64_t total = 0, written = 0;
    for (int c = 0; c < n_chunks; ++c) {
        total += counts[c];
        for (int64_t off : hits[c])
            if (written < cap) offsets[written++] = off;
    }
    *n_written = written;
    return total;
}

}  // extern "C"
