"""ctypes bindings for the native runtime library, built on demand.

One shared object holds every native component — image staging
(`stage.cc`, the data-loader hot path) and the log-scan engine
(`grepscan.cc`, the distributed-grep hot path). Built with
``g++ -O3 -march=native -fopenmp`` at first use (cached next to the
sources, keyed by their joint hash); every entry point has a pure-Python
fallback so the framework works without a toolchain — native is an
accelerator, not a dependency (the environment provides g++ but no
pybind11, hence ctypes).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = [os.path.join(_DIR, "stage.cc"),
            os.path.join(_DIR, "grepscan.cc")]
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> ctypes.CDLL | None:
    h = hashlib.sha256()
    for src in _SOURCES:
        with open(src, "rb") as f:
            h.update(f.read())
    tag = h.hexdigest()[:16]
    so_path = os.path.join(_DIR, f"_native_{tag}.so")
    if not os.path.exists(so_path):
        # pid-unique temp so concurrent builds from several local node
        # processes can't interleave writes; os.replace publishes atomically
        tmp = f"{so_path}.{os.getpid()}.tmp"
        cmd = ["g++", "-O3", "-march=native", "-fopenmp", "-shared",
               "-fPIC", *_SOURCES, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so_path)
        except (subprocess.SubprocessError, OSError, FileNotFoundError):
            return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None
    lib.resize_bilinear_u8.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int]
    lib.stage_batch_u8.argtypes = [
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8)]
    lib.grep_literal.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64)]
    lib.grep_literal.restype = ctypes.c_int64
    return lib


def get_lib() -> ctypes.CDLL | None:
    global _lib, _tried
    if _lib is None and not _tried:
        with _lock:
            if _lib is None and not _tried:
                _lib = _build()
                _tried = True
    return _lib


def available() -> bool:
    return get_lib() is not None


def _as_u8_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _axis_coords(s: int, d: int):
    """Half-pixel 16.16 fixed-point source coordinates for one axis —
    bit-identical to stage.cc's ``x * x_step + x_step/2 - 2^15`` clamped."""
    step = (s << 16) // d
    c = np.arange(d, dtype=np.int64) * step + step // 2 - (1 << 15)
    np.clip(c, 0, (s - 1) << 16, out=c)
    lo = c >> 16
    hi = np.minimum(lo + 1, s - 1)
    frac = c & 0xFFFF
    return lo, hi, frac


def _resize_bilinear_np(src: np.ndarray, dh: int, dw: int) -> np.ndarray:
    """Pure-numpy twin of stage.cc's resize_bilinear_u8 (same fixed point,
    same rounding) so staging is pixel-identical with or without g++."""
    sh, sw = src.shape[:2]
    y0, y1, fy = _axis_coords(sh, dh)
    x0, x1, fx = _axis_coords(sw, dw)
    p = src.astype(np.int64)
    r0, r1 = p[y0], p[y1]                       # [dh, sw, 3]
    top = (r0[:, x0] << 16) + (r0[:, x1] - r0[:, x0]) * fx[None, :, None]
    bot = (r1[:, x0] << 16) + (r1[:, x1] - r1[:, x0]) * fx[None, :, None]
    val = (top << 16) + (bot - top) * fy[:, None, None]
    return ((val + (1 << 31)) >> 32).astype(np.uint8)


def resize_bilinear(src: np.ndarray, dh: int, dw: int) -> np.ndarray:
    """RGB u8 [H, W, 3] → [dh, dw, 3]; native when possible, bit-identical
    numpy fallback otherwise."""
    lib = get_lib()
    if lib is None:
        return _resize_bilinear_np(
            np.ascontiguousarray(src, dtype=np.uint8), dh, dw)
    src = np.ascontiguousarray(src, dtype=np.uint8)
    dst = np.empty((dh, dw, 3), np.uint8)
    lib.resize_bilinear_u8(_as_u8_ptr(src), src.shape[0], src.shape[1],
                           _as_u8_ptr(dst), dh, dw)
    return dst


def _stage_batch_np(frames: list[np.ndarray], size: int) -> np.ndarray:
    out = np.empty((len(frames), size, size, 3), np.uint8)
    for i, f in enumerate(frames):
        h, w = f.shape[:2]
        # rounded division, same integer formula as stage.cc
        if w <= h:
            rw, rh = size, max(size, (h * size + w // 2) // w)
        else:
            rh, rw = size, max(size, (w * size + h // 2) // h)
        r = _resize_bilinear_np(
            np.ascontiguousarray(f, dtype=np.uint8), rh, rw)
        top, left = (rh - size) // 2, (rw - size) // 2
        out[i] = r[top:top + size, left:left + size]
    return out


def stage_batch(frames: list[np.ndarray], size: int) -> np.ndarray:
    """K decoded RGB frames (varying sizes) → contiguous u8
    [K, size, size, 3] with shortest-side resize + center crop. OpenMP
    across frames natively; bit-identical serial numpy fallback otherwise."""
    lib = get_lib()
    if lib is None or not frames:
        return _stage_batch_np(frames, size)
    contig = [np.ascontiguousarray(f, dtype=np.uint8) for f in frames]
    k = len(contig)
    ptrs = (ctypes.POINTER(ctypes.c_uint8) * k)(
        *[_as_u8_ptr(f) for f in contig])
    dims = np.asarray([[f.shape[0], f.shape[1]] for f in contig],
                      dtype=np.int32)
    dst = np.empty((k, size, size, 3), np.uint8)
    lib.stage_batch_u8(ptrs, dims.ctypes.data_as(
        ctypes.POINTER(ctypes.c_int32)), k, size, _as_u8_ptr(dst))
    return dst


def grep_literal(path: str, needle: str,
                 max_offsets: int = 10_000) -> tuple[int, list[int]] | None:
    """Count lines of ``path`` containing the literal ``needle``; also
    return up to ``max_offsets`` matching line-start byte offsets
    (ascending). None when the native library is unavailable (caller falls
    back to the Python scanner) or the file cannot be read."""
    lib = get_lib()
    if lib is None:
        return None
    offsets = np.empty(max_offsets, np.int64)
    n_written = ctypes.c_int64(0)
    total = lib.grep_literal(
        path.encode(), needle.encode(),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        max_offsets, ctypes.byref(n_written))
    if total < 0:
        return None
    return int(total), offsets[:n_written.value].tolist()
