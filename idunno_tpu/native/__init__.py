"""ctypes binding for the native staging library, built on demand.

``g++ -O3 -march=native -fopenmp`` at first use (cached next to the source,
keyed by source hash); every entry point has a numpy/PIL fallback so the
framework works without a toolchain — native is an accelerator, not a
dependency (the environment provides g++ but no pybind11, hence ctypes).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "stage.cc")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> ctypes.CDLL | None:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    so_path = os.path.join(_DIR, f"_stage_{tag}.so")
    if not os.path.exists(so_path):
        # pid-unique temp so concurrent builds from several local node
        # processes can't interleave writes; os.replace publishes atomically
        tmp = f"{so_path}.{os.getpid()}.tmp"
        cmd = ["g++", "-O3", "-march=native", "-fopenmp", "-shared",
               "-fPIC", _SRC, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so_path)
        except (subprocess.SubprocessError, OSError, FileNotFoundError):
            return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None
    lib.resize_bilinear_u8.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int]
    lib.stage_batch_u8.argtypes = [
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8)]
    return lib


def get_lib() -> ctypes.CDLL | None:
    global _lib, _tried
    if _lib is None and not _tried:
        with _lock:
            if _lib is None and not _tried:
                _lib = _build()
                _tried = True
    return _lib


def available() -> bool:
    return get_lib() is not None


def _as_u8_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def resize_bilinear(src: np.ndarray, dh: int, dw: int) -> np.ndarray:
    """RGB u8 [H, W, 3] → [dh, dw, 3]; native when possible, PIL fallback."""
    lib = get_lib()
    if lib is None:
        from PIL import Image
        img = Image.fromarray(src).resize((dw, dh), Image.BILINEAR)
        return np.asarray(img, dtype=np.uint8)
    src = np.ascontiguousarray(src, dtype=np.uint8)
    dst = np.empty((dh, dw, 3), np.uint8)
    lib.resize_bilinear_u8(_as_u8_ptr(src), src.shape[0], src.shape[1],
                           _as_u8_ptr(dst), dh, dw)
    return dst


def stage_batch(frames: list[np.ndarray], size: int) -> np.ndarray:
    """K decoded RGB frames (varying sizes) → contiguous u8
    [K, size, size, 3] with shortest-side resize + center crop. OpenMP
    across frames natively; serial numpy/PIL fallback otherwise."""
    lib = get_lib()
    if lib is None or not frames:
        out = np.empty((len(frames), size, size, 3), np.uint8)
        for i, f in enumerate(frames):
            h, w = f.shape[:2]
            if w <= h:
                rw, rh = size, max(size, round(h * size / w))
            else:
                rh, rw = size, max(size, round(w * size / h))
            r = resize_bilinear(f, rh, rw)
            top, left = (rh - size) // 2, (rw - size) // 2
            out[i] = r[top:top + size, left:left + size]
        return out
    contig = [np.ascontiguousarray(f, dtype=np.uint8) for f in frames]
    k = len(contig)
    ptrs = (ctypes.POINTER(ctypes.c_uint8) * k)(
        *[_as_u8_ptr(f) for f in contig])
    dims = np.asarray([[f.shape[0], f.shape[1]] for f in contig],
                      dtype=np.int32)
    dst = np.empty((k, size, size, 3), np.uint8)
    lib.stage_batch_u8(ptrs, dims.ctypes.data_as(
        ctypes.POINTER(ctypes.c_int32)), k, size, _as_u8_ptr(dst))
    return dst
