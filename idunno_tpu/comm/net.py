"""Real socket transport: JSON/length-framed TCP + UDP datagrams over DCN.

Replaces the reference's five per-port listeners with hand-rolled
``"<SEPARATOR>"`` string frames and 4096-byte buffers
(`mp4_machinelearning.py:29-42, 54-55`): one TCP listener + one UDP socket
per node, length-prefixed binary frames (no delimiter collisions, no partial
-read truncation), service routing in the frame header, blob-safe file
streaming.

Addressing is injected (``addr_of: host -> (ip, tcp_port, udp_port)``) so
nothing is hardcoded (the reference hardcodes the master IP at four call
sites, `:922-939`).
"""
from __future__ import annotations

import logging
import socket
import struct
import threading
import time
from collections.abc import Callable

from idunno_tpu.comm.message import Message
from idunno_tpu.comm.transport import Handler, Transport, TransportError

AddrOf = Callable[[str], tuple[str, int, int]]   # (ip, tcp_port, udp_port)

log = logging.getLogger("idunno.net")

_MAX_FRAME = 1 << 31


def _send_frame(sock: socket.socket, service: str, msg: Message) -> None:
    svc = service.encode()
    body = msg.to_bytes()
    sock.sendall(struct.pack(">HI", len(svc), len(body)) + svc + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> tuple[str, Message]:
    head = _recv_exact(sock, 6)
    svc_len, body_len = struct.unpack(">HI", head)
    if body_len > _MAX_FRAME:
        raise ConnectionError("oversized frame")
    svc = _recv_exact(sock, svc_len).decode()
    body = _recv_exact(sock, body_len)
    return svc, Message.from_bytes(body)


def oneshot_call(ip: str, tcp_port: int, service: str, msg: Message,
                 timeout: float = 10.0) -> Message | None:
    """Pure-client RPC: one framed request/response on a fresh connection,
    no listener bound — how external tools (tests, ops scripts, the remote
    CLI) talk to a node without becoming one. A peer that closes without
    sending a reply frame raises a typed ``closed`` TransportError (every
    service in this codebase replies over TCP, so a bare close means the
    handler died mid-request — retryable, not a silent None)."""
    with socket.create_connection((ip, tcp_port), timeout=timeout) as sock:
        _send_frame(sock, service, msg)
        sock.shutdown(socket.SHUT_WR)
        try:
            _, out = _recv_frame(sock)
            return out
        except ConnectionError as e:
            raise TransportError(f"{ip}:{tcp_port} closed before reply: {e}",
                                 reason="closed") from e


class NetTransport(Transport):
    def __init__(self, host: str, addr_of: AddrOf, bind_ip: str = "0.0.0.0",
                 accept_timeout: float = 0.2) -> None:
        self.host = host
        self._addr_of = addr_of
        self._handlers: dict[str, Handler] = {}
        self._stop = threading.Event()
        # latency source for the optional health feed (injectable)
        self.clock = time.monotonic

        my_ip, tcp_port, udp_port = addr_of(host)
        self._tcp = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._tcp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._tcp.bind((bind_ip, tcp_port))
        self._tcp.listen(64)
        self._tcp.settimeout(accept_timeout)

        self._udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._udp.bind((bind_ip, udp_port))
        self._udp.settimeout(accept_timeout)

        self._threads = [
            threading.Thread(target=self._tcp_loop, daemon=True,
                             name=f"{host}-tcp"),
            threading.Thread(target=self._udp_loop, daemon=True,
                             name=f"{host}-udp"),
        ]
        for t in self._threads:
            t.start()

    # -- server side ------------------------------------------------------

    def serve(self, service: str, handler: Handler) -> None:
        self._handlers[service] = handler

    def _tcp_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._tcp.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        svc = "?"
        try:
            with conn:
                conn.settimeout(30.0)
                svc, msg = _recv_frame(conn)
                handler = self._handlers.get(svc)
                out = handler(svc, msg) if handler else None
                if out is not None:
                    _send_frame(conn, svc, out)
        except (ConnectionError, socket.timeout, OSError):
            pass
        except Exception:  # noqa: BLE001 - malformed frame body or a
            # handler bug: drop THIS connection (the client sees a close
            # and errors/retries) but log it instead of spraying a raw
            # thread traceback — the listener itself keeps serving
            log.exception("connection handler error (service %s)", svc)

    def _udp_loop(self) -> None:
        while not self._stop.is_set():
            try:
                data, _ = self._udp.recvfrom(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                svc_len, body_len = struct.unpack(">HI", data[:6])
                svc = data[6:6 + svc_len].decode()
                msg = Message.from_bytes(data[6 + svc_len:6 + svc_len + body_len])
            except Exception:
                continue
            handler = self._handlers.get(svc)
            if handler:
                try:
                    handler(svc, msg)     # datagrams never reply
                except Exception:  # noqa: BLE001 - a handler bug must not
                    # kill the UDP loop: this thread carries every
                    # heartbeat/gossip datagram for the node, and its
                    # silent death would make peers falsely suspect us
                    log.exception("datagram handler error (service %s)",
                                  svc)

    # -- client side ------------------------------------------------------

    def call(self, host: str, service: str, msg: Message,
             timeout: float | None = None) -> Message | None:
        # typed failure reasons instead of one blanket bucket: the retry
        # layer (comm/retry.py) backs off on timeout/refused/closed but a
        # caller can still tell "peer busy" from "peer gone". Order
        # matters: socket.timeout ⊂ OSError, ConnectionRefusedError ⊂
        # ConnectionError ⊂ OSError.
        ip, tcp_port, _ = self._addr_of(host)
        # differential health feed (membership/health.py): real wall
        # latency per call when a ledger is attached. The clock is an
        # injectable attribute so tests can pin it; NetTransport never
        # runs under the seeded chaos harness.
        h = self.health
        t0 = self.clock() if h is not None else 0.0
        try:
            out = oneshot_call(ip, tcp_port, service, msg,
                               timeout=timeout or 10.0)
        except socket.timeout as e:
            if h is not None:
                h.observe(host, self.clock() - t0, error=True)
            raise TransportError(f"{host} timed out: {e}",
                                 reason="timeout") from e
        except ConnectionRefusedError as e:
            if h is not None:
                h.observe(host, self.clock() - t0, error=True)
            raise TransportError(f"{host} refused: {e}",
                                 reason="refused") from e
        except ConnectionError as e:
            if h is not None:
                h.observe(host, self.clock() - t0, error=True)
            raise TransportError(f"{host} closed connection: {e}",
                                 reason="closed") from e
        except OSError as e:
            if h is not None:
                h.observe(host, self.clock() - t0, error=True)
            raise TransportError(f"{host} unreachable: {e}") from e
        if h is not None:
            h.observe(host, self.clock() - t0)
        return out

    def datagram(self, host: str, service: str, msg: Message) -> None:
        try:
            ip, _, udp_port = self._addr_of(host)
            svc = service.encode()
            body = msg.to_bytes()
            self._udp.sendto(struct.pack(">HI", len(svc), len(body)) + svc
                             + body, (ip, udp_port))
        except OSError:
            pass

    def close(self) -> None:
        self._stop.set()
        for s in (self._tcp, self._udp):
            try:
                s.close()
            except OSError:
                pass
