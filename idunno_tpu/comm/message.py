"""Typed control-plane messages.

The reference's wire format is ad hoc: JSON dicts over UDP for membership
(`mp4_machinelearning.py:183-184, 212-213`) and ``"<SEPARATOR>"``-joined
string frames over TCP for everything else (`:54`, e.g. `:800-801`) — with
the documented ``receive_metadata`` corruption bug where raw strings are
assigned over dict-typed fields (`:989-1011`, SURVEY.md §7 "bugs not to
replicate"). Here every message is one typed envelope with a JSON-object
payload, the same shape on every service.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any

from idunno_tpu.utils.types import MessageType


@dataclass
class Message:
    type: MessageType
    sender: str
    payload: dict[str, Any] = field(default_factory=dict)
    # Raw bytes rider for file content — framed separately so payloads stay
    # printable JSON (the reference streams file bytes on the same socket
    # after a string header, `mp4_machinelearning.py:103-111`).
    blob: bytes = b""

    def to_bytes(self) -> bytes:
        head = json.dumps({"type": self.type.value, "sender": self.sender,
                           "payload": self.payload}).encode()
        return (len(head).to_bytes(4, "big") + head
                + len(self.blob).to_bytes(8, "big") + self.blob)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Message":
        hlen = int.from_bytes(data[:4], "big")
        head = json.loads(data[4:4 + hlen].decode())
        boff = 4 + hlen
        blen = int.from_bytes(data[boff:boff + 8], "big")
        blob = data[boff + 8:boff + 8 + blen]
        return cls(type=MessageType(head["type"]), sender=head["sender"],
                   payload=head["payload"], blob=blob)
