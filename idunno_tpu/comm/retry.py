"""Bounded exponential backoff with jitter for control-plane RPC.

The reference retries nothing: a lost ACK surfaces as a client error and a
re-submit double-books (`mp4_machinelearning.py:956-963` fails over
primary→standby exactly once, then gives up). Here mutating verbs carry
client-generated idempotency keys deduped server-side (submit / lm_submit /
SDFS put), which makes retrying safe — so the transport layer can retry
typed-retryable faults (timeout/refused/closed/unreachable) under a
deadline without risking duplicate work. ``stale_epoch`` rejections are
never retried: a fenced coordinator must step down, not hammer the new one
(membership/epoch.py).

Full jitter (delay × U[0.5, 1)) decorrelates the retry storms of many
clients hitting one recovering coordinator. Defaults are small (3 attempts
from 20 ms) because callers sit in front of their own failover loops —
this layer only rides out blips, it does not replace them.
"""
from __future__ import annotations

import random
import threading
import time
from collections.abc import Callable

from idunno_tpu.comm.transport import TransportError

# process-wide retry accounting (ISSUE 6 satellite): PR 5 logged retries
# but never counted them. Module-level because this helper has no node
# handle — `metrics_export` (serve/control.py) merges these into the
# Prometheus exposition, and `counters()` consumers read them via
# `retry_counters()`. Thread-safe; reset only in tests.
_counters_lock = threading.Lock()
_counters = {"retry_attempts": 0, "retry_exhausted": 0,
             "hedged_rpcs": 0, "hedge_wins": 0}


def _count(name: str, n: int = 1) -> None:
    with _counters_lock:
        _counters[name] += n


def retry_counters() -> dict[str, int]:
    """Snapshot of the process-wide retry counters."""
    with _counters_lock:
        return dict(_counters)


def reset_retry_counters() -> None:
    """Test hook: zero the process-wide counters."""
    with _counters_lock:
        for k in _counters:
            _counters[k] = 0


def call_with_retry(fn: Callable[[], object], *, attempts: int = 3,
                    base_s: float = 0.02, cap_s: float = 0.25,
                    deadline_s: float = 2.0,
                    rng: random.Random | None = None,
                    sleep: Callable[[float], None] = time.sleep,
                    clock: Callable[[], float] = time.monotonic):
    """Run ``fn`` retrying retryable TransportErrors with exponential
    backoff + jitter, bounded by both ``attempts`` and ``deadline_s``.
    Non-retryable errors (e.g. StaleEpoch) and non-transport exceptions
    propagate immediately."""
    roll = (rng or random).random
    t0 = clock()
    delay = base_s
    last: TransportError | None = None
    for attempt in range(max(1, attempts)):
        try:
            return fn()
        except TransportError as e:
            if not getattr(e, "retryable", True):
                raise
            last = e
        if attempt + 1 >= attempts:
            break
        pause = delay * (0.5 + 0.5 * roll())
        if clock() - t0 + pause > deadline_s:
            break
        _count("retry_attempts")
        sleep(pause)
        delay = min(delay * 2.0, cap_s)
    assert last is not None
    _count("retry_exhausted")
    raise last


def call_hedged(fns, *, delay_s: float = 0.05,
                on_late: Callable[[object], None] | None = None):
    """Tail-hedged read (Dean & Barroso, *The Tail at Scale*, CACM 2013):
    fire ``fns[0]``; if it has not answered within ``delay_s``, fire the
    backup thunks too and return the FIRST success. Every call site must
    be declared in ``contracts.HEDGE_SAFE`` with idempotent READ verbs
    only (machine-checked by protocol_lint's hedge checker) — a hedged
    mutation lands twice.

    ``on_late(result)`` receives each losing thunk's eventual success so
    callers with delivery-marking reads (lm_poll) can merge rather than
    lose the duplicate's rows. Late *failures* are discarded.

    Single-thunk (or non-positive delay with one fn) degenerates to a
    plain call: no thread, no counter. NOT for the chaos harness —
    hedge threads would interleave the seeded rng draws; `hedge_reads`
    stays off there by config default.
    """
    fns = list(fns)
    if not fns:
        raise ValueError("call_hedged needs at least one thunk")
    if len(fns) == 1:
        return fns[0]()

    done = threading.Event()
    lock = threading.Lock()
    results: list[tuple[int, object]] = []    # (thunk index, value)
    errors: list[BaseException] = []
    launched = [False] * len(fns)

    def run(i: int) -> None:
        try:
            out = fns[i]()
        except BaseException as e:  # noqa: BLE001 - collected, re-raised
            with lock:
                errors.append(e)
                all_failed = len(errors) == sum(launched)
            if all_failed:
                done.set()
            return
        late = None
        with lock:
            results.append((i, out))
            late = len(results) > 1
        if late and on_late is not None:
            on_late(out)
        done.set()

    threads = []
    with lock:
        launched[0] = True
    t0 = threading.Thread(target=run, args=(0,), daemon=True)
    threads.append(t0)
    t0.start()
    if not done.wait(max(0.0, delay_s)):
        _count("hedged_rpcs")
        for i in range(1, len(fns)):
            with lock:
                launched[i] = True
            t = threading.Thread(target=run, args=(i,), daemon=True)
            threads.append(t)
            t.start()
    # first success wins; if every launched thunk failed, re-raise the
    # last error. Clear-before-check: appends happen under the lock
    # strictly before set(), so nothing observable is lost to the clear.
    while True:
        done.wait()
        done.clear()
        with lock:
            if results:
                idx, out = results[0]
                if idx > 0:
                    _count("hedge_wins")
                return out
            if len(errors) == sum(launched):
                raise errors[-1]
