"""In-process fake network — the test cluster substrate.

The reference was only ever tested by hand on 10 real VMs (SURVEY.md §4).
This transport lets N node objects form a cluster inside one process with
controllable failures: ``kill(host)`` makes a node unreachable (process
crash), ``partition(a, b)`` drops traffic between two hosts (network cut),
both reversible. Delivery is synchronous on the caller's thread — tests stay
deterministic; the node runtime supplies its own threads for periodic loops.
"""
from __future__ import annotations

import threading

from idunno_tpu.comm.message import Message
from idunno_tpu.comm.transport import Handler, Transport, TransportError


class InProcNetwork:
    """Shared registry of node transports + fault state."""

    def __init__(self) -> None:
        self._nodes: dict[str, "InProcTransport"] = {}
        self._dead: set[str] = set()
        self._cuts: set[frozenset[str]] = set()
        self._lock = threading.RLock()

    def transport(self, host: str) -> "InProcTransport":
        with self._lock:
            t = InProcTransport(host, self)
            self._nodes[host] = t
            return t

    # -- fault injection --------------------------------------------------

    def kill(self, host: str) -> None:
        with self._lock:
            self._dead.add(host)

    def revive(self, host: str) -> None:
        with self._lock:
            self._dead.discard(host)

    def partition(self, a: str, b: str) -> None:
        with self._lock:
            self._cuts.add(frozenset((a, b)))

    def heal(self, a: str, b: str) -> None:
        with self._lock:
            self._cuts.discard(frozenset((a, b)))

    # -- delivery ---------------------------------------------------------

    def _reachable(self, src: str, dst: str) -> bool:
        with self._lock:
            return (dst in self._nodes and dst not in self._dead
                    and src not in self._dead
                    and frozenset((src, dst)) not in self._cuts)

    def deliver(self, src: str, dst: str, service: str,
                msg: Message, reliable: bool) -> Message | None:
        if not self._reachable(src, dst):
            if reliable:
                raise TransportError(f"{dst} unreachable from {src}")
            return None
        with self._lock:
            node = self._nodes[dst]
            handler = node._handlers.get(service)
        if handler is None:
            if reliable:
                raise TransportError(f"{dst} has no service {service!r}")
            return None
        # round-trip through bytes so serialization bugs surface in tests
        wire = Message.from_bytes(msg.to_bytes())
        return handler(service, wire)


class InProcTransport(Transport):
    def __init__(self, host: str, net: InProcNetwork) -> None:
        self.host = host
        self._net = net
        self._handlers: dict[str, Handler] = {}

    def serve(self, service: str, handler: Handler) -> None:
        self._handlers[service] = handler

    def call(self, host: str, service: str, msg: Message,
             timeout: float | None = None) -> Message | None:
        return self._net.deliver(self.host, host, service, msg, reliable=True)

    def datagram(self, host: str, service: str, msg: Message) -> None:
        try:
            self._net.deliver(self.host, host, service, msg, reliable=False)
        except TransportError:
            pass

    def close(self) -> None:
        self._handlers.clear()
