"""In-process fake network — the test cluster substrate.

The reference was only ever tested by hand on 10 real VMs (SURVEY.md §4).
This transport lets N node objects form a cluster inside one process with
controllable failures: ``kill(host)`` makes a node unreachable (process
crash), ``partition(a, b)`` drops traffic between two hosts (network cut),
both reversible. Delivery is synchronous on the caller's thread — tests stay
deterministic; the node runtime supplies its own threads for periodic loops.

Beyond binary kill/cut, the network injects *seeded* partial faults in the
style of FoundationDB's deterministic simulation (Zhou et al., SIGMOD 2021):

- ``cut_oneway(src, dst)`` — asymmetric loss: src→dst traffic is dropped
  while dst→src still flows (requests lost one way; replies lost the
  other — a reliable call whose *reply* direction is cut runs the handler
  and then raises, the exact lost-ACK shape idempotency keys exist for).
- ``set_chaos(drop=…, dup=…, delay=…, seed=…)`` — probabilistic drop,
  duplication (handler runs twice per request), and bounded delay/reorder
  of datagrams, drawn from one ``random.Random(seed)`` so a failing chaos
  schedule replays exactly from its seed.
- ``lose_next_reply(src, dst, n)`` — a targeted, deterministic lost ACK:
  the next ``n`` reliable calls src→dst execute server-side but the caller
  sees a timeout.
- ``slow_host(host, factor)`` — a sustained FAIL-SLOW fault (ISSUE 20,
  gray failure): every call touching the host reports an inflated
  handler latency (``base_call_s × factor``) to the caller's attached
  health ledger — a *handler-delay multiplier*, distinct from the
  per-datagram ``delay`` reordering above. Deterministic (no clock, no
  rng): the latency is synthesized, not slept, unless ``sleep_s`` is
  given (the gray bench uses a real sleep so hedging has a real tail to
  cut). Cleared by ``clear_chaos``.

Chaos is off by default (all probabilities 0, no cuts): existing fixtures
burn no RNG draws and behave exactly as before.
"""
from __future__ import annotations

import random
import threading
import time

from idunno_tpu.comm.message import Message
from idunno_tpu.comm.transport import Handler, Transport, TransportError


class InProcNetwork:
    """Shared registry of node transports + fault state."""

    def __init__(self, seed: int | None = None) -> None:
        self._nodes: dict[str, "InProcTransport"] = {}
        self._dead: set[str] = set()
        self._cuts: set[frozenset[str]] = set()
        self._oneway: set[tuple[str, str]] = set()
        self._lose_reply: dict[tuple[str, str], int] = {}
        self._rng = random.Random(seed)
        self._drop_p = 0.0
        self._dup_p = 0.0
        self._delay_p = 0.0
        self._delay_max = 4
        self._chaos_links: set[tuple[str, str]] | None = None
        # held datagrams: [deliveries_left_until_release, src, dst,
        # service, msg] — releasing after N subsequent delivers gives
        # bounded delay AND reordering without a clock dependency
        self._held: list[list] = []
        # fail-slow fault state: host -> (latency multiplier, real sleep)
        self._slow: dict[str, tuple[float, float]] = {}
        # nominal per-call handler latency reported to health ledgers
        # when no fault is active (everything equally fast = no verdicts)
        self.base_call_s = 0.01
        self._lock = threading.RLock()

    def transport(self, host: str) -> "InProcTransport":
        with self._lock:
            t = InProcTransport(host, self)
            self._nodes[host] = t
            return t

    # -- fault injection --------------------------------------------------

    def kill(self, host: str) -> None:
        with self._lock:
            self._dead.add(host)

    def revive(self, host: str) -> None:
        with self._lock:
            self._dead.discard(host)

    def partition(self, a: str, b: str) -> None:
        with self._lock:
            self._cuts.add(frozenset((a, b)))

    def heal(self, a: str, b: str) -> None:
        with self._lock:
            self._cuts.discard(frozenset((a, b)))

    def cut_oneway(self, src: str, dst: str) -> None:
        """Drop src→dst traffic only (dst→src still flows)."""
        with self._lock:
            self._oneway.add((src, dst))

    def heal_oneway(self, src: str, dst: str) -> None:
        with self._lock:
            self._oneway.discard((src, dst))

    def lose_next_reply(self, src: str, dst: str, n: int = 1) -> None:
        """The next ``n`` reliable calls src→dst run the handler but the
        caller gets a timeout — a deterministic lost ACK."""
        with self._lock:
            self._lose_reply[(src, dst)] = (
                self._lose_reply.get((src, dst), 0) + n)

    def set_chaos(self, *, drop: float = 0.0, dup: float = 0.0,
                  delay: float = 0.0, max_delay: int = 4,
                  seed: int | None = None,
                  links=None) -> None:
        """Enable probabilistic faults on every delivery (or only on
        ``links``, an iterable of (src, dst) pairs). ``drop``/``dup``/
        ``delay`` are per-delivery probabilities; a dropped reliable call
        splits 50/50 between lost-request and lost-reply. Reseeds the
        schedule RNG when ``seed`` is given."""
        with self._lock:
            self._drop_p = float(drop)
            self._dup_p = float(dup)
            self._delay_p = float(delay)
            self._delay_max = max(1, int(max_delay))
            self._chaos_links = (None if links is None
                                 else {tuple(l) for l in links})
            if seed is not None:
                self._rng = random.Random(seed)

    def slow_host(self, host: str, factor: float,
                  sleep_s: float = 0.0) -> None:
        """Sustained fail-slow: calls to/from ``host`` report
        ``base_call_s × factor`` latency to attached health ledgers
        (and really sleep ``sleep_s`` when given — bench only; chaos
        stays sleepless so fake clocks own time)."""
        with self._lock:
            self._slow[host] = (max(1.0, float(factor)), float(sleep_s))

    def clear_slow(self, host: str | None = None) -> None:
        with self._lock:
            if host is None:
                self._slow.clear()
            else:
                self._slow.pop(host, None)

    def call_latency(self, src: str, dst: str) -> float:
        """Synthesized handler latency for one reliable call src→dst —
        what the caller's health ledger observes. Pure function of the
        fault state: deterministic under seeded schedules."""
        with self._lock:
            f = max(self._slow.get(dst, (1.0, 0.0))[0],
                    self._slow.get(src, (1.0, 0.0))[0])
            return self.base_call_s * f

    def clear_chaos(self) -> None:
        with self._lock:
            self._drop_p = self._dup_p = self._delay_p = 0.0
            self._chaos_links = None
            self._lose_reply.clear()
            self._slow.clear()

    def heal_all(self) -> None:
        """Remove every cut (symmetric and one-way); chaos probabilities
        and held datagrams are untouched (clear_chaos / flush_held)."""
        with self._lock:
            self._cuts.clear()
            self._oneway.clear()

    def unperturbed(self, host: str) -> bool:
        """True when ``host`` is alive and no cut (symmetric or one-way)
        touches it — the precondition for the chaos harness's
        false-LEAVE invariant: a merely SLOW host with clean links must
        never be declared dead."""
        with self._lock:
            return (host not in self._dead
                    and not any(host in c for c in self._cuts)
                    and not any(host in pair for pair in self._oneway))

    def flush_held(self) -> None:
        """Deliver every delayed datagram now (still subject to the
        *current* reachability — a heal then flush models late packets
        crossing the healed link)."""
        with self._lock:
            due, self._held = self._held, []
        for _, src, dst, service, msg in due:
            self._release_one(src, dst, service, msg)

    # -- delivery ---------------------------------------------------------

    def _reachable(self, src: str, dst: str) -> bool:
        with self._lock:
            return (dst in self._nodes and dst not in self._dead
                    and src not in self._dead
                    and frozenset((src, dst)) not in self._cuts
                    and (src, dst) not in self._oneway)

    def _chaos_roll(self, src: str, dst: str, reliable: bool) -> str:
        with self._lock:
            total = self._drop_p + self._dup_p + self._delay_p
            if total <= 0.0:
                return "ok"
            if (self._chaos_links is not None
                    and (src, dst) not in self._chaos_links):
                return "ok"
            r = self._rng.random()
            if r < self._drop_p:
                if reliable and self._rng.random() < 0.5:
                    return "drop_reply"
                return "drop"
            if r < self._drop_p + self._dup_p:
                return "dup"
            if r < total:
                return "delay"
            return "ok"

    def _tick_held(self) -> None:
        """Each delivery ages held datagrams by one; release the due ones
        (re-checking reachability at release time, like real late
        packets)."""
        with self._lock:
            if not self._held:
                return
            keep: list[list] = []
            due: list[list] = []
            for item in self._held:
                item[0] -= 1
                (due if item[0] <= 0 else keep).append(item)
            self._held = keep
        for _, src, dst, service, msg in due:
            self._release_one(src, dst, service, msg)

    def _release_one(self, src: str, dst: str, service: str,
                     msg: Message) -> None:
        try:
            if self._reachable(src, dst):
                self._deliver_raw(src, dst, service, msg, reliable=False)
        except TransportError:
            pass

    def _deliver_raw(self, src: str, dst: str, service: str,
                     msg: Message, reliable: bool) -> Message | None:
        with self._lock:
            node = self._nodes.get(dst)
            handler = node._handlers.get(service) if node else None
        if handler is None:
            if reliable:
                raise TransportError(f"{dst} has no service {service!r}",
                                     reason="closed")
            return None
        # round-trip through bytes so serialization bugs surface in tests
        wire = Message.from_bytes(msg.to_bytes())
        return handler(service, wire)

    def deliver(self, src: str, dst: str, service: str,
                msg: Message, reliable: bool) -> Message | None:
        self._tick_held()
        if not self._reachable(src, dst):
            if reliable:
                raise TransportError(f"{dst} unreachable from {src}")
            return None
        mode = self._chaos_roll(src, dst, reliable)
        with self._lock:
            rev_cut = (dst, src) in self._oneway
            lose_reply = self._lose_reply.get((src, dst), 0) > 0
            if reliable and lose_reply:
                self._lose_reply[(src, dst)] -= 1
        if reliable:
            if mode == "drop":
                raise TransportError(
                    f"request {src}->{dst} dropped (chaos)",
                    reason="timeout")
            with self._lock:
                naps = max(self._slow.get(dst, (1.0, 0.0))[1],
                           self._slow.get(src, (1.0, 0.0))[1])
            if naps > 0.0:
                # bench-mode fail-slow only: chaos schedules keep
                # sleep_s=0 so the fake clock owns all time
                time.sleep(naps)
            # delay is unobservable on a synchronous call — deliver
            out = self._deliver_raw(src, dst, service, msg, reliable=True)
            if mode == "dup":    # duplicated request frame: handler twice
                self._deliver_raw(src, dst, service, msg, reliable=True)
            if mode == "drop_reply" or rev_cut or lose_reply:
                raise TransportError(
                    f"reply {dst}->{src} lost from {src}'s view",
                    reason="timeout")
            return out
        if mode == "drop":
            return None
        if mode == "delay":
            with self._lock:
                hold = 1 + self._rng.randrange(self._delay_max)
                self._held.append([hold, src, dst, service, msg])
            return None
        out = self._deliver_raw(src, dst, service, msg, reliable=False)
        if mode == "dup":
            self._deliver_raw(src, dst, service, msg, reliable=False)
        return out


class InProcTransport(Transport):
    def __init__(self, host: str, net: InProcNetwork) -> None:
        self.host = host
        self._net = net
        self._handlers: dict[str, Handler] = {}

    def serve(self, service: str, handler: Handler) -> None:
        self._handlers[service] = handler

    def call(self, host: str, service: str, msg: Message,
             timeout: float | None = None) -> Message | None:
        h = self.health
        if h is None:
            return self._net.deliver(self.host, host, service, msg,
                                     reliable=True)
        # differential health feed: the synthesized per-call latency is a
        # pure function of the network's fail-slow state, so seeded chaos
        # schedules observe identical samples on replay
        lat = self._net.call_latency(self.host, host)
        try:
            out = self._net.deliver(self.host, host, service, msg,
                                    reliable=True)
        except TransportError:
            h.observe(host, lat, error=True)
            raise
        h.observe(host, lat)
        return out

    def datagram(self, host: str, service: str, msg: Message) -> None:
        try:
            self._net.deliver(self.host, host, service, msg, reliable=False)
        except TransportError:
            pass

    def close(self) -> None:
        self._handlers.clear()
