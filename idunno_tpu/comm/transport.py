"""Transport abstraction for the DCN control plane.

The reference binds five raw sockets per node (ports `mp4_machinelearning.py
:29-42`) and hand-codes connect/send/recv at every call site. Here a node
talks to a named (host, service) endpoint through one interface with two
delivery modes, and the wire substrate is pluggable:

- ``InProcTransport`` (comm/inproc.py) — loopback delivery inside one
  process, for the fake-cluster test fixture (SURVEY.md §4).
- ``NetTransport`` (comm/net.py) — JSON-over-TCP with length framing plus
  UDP datagrams, for real multi-host deployments over DCN.

Services (the reference's ports): membership, store, inference, result,
metadata, grep.
"""
from __future__ import annotations

import abc
from collections.abc import Callable

from idunno_tpu.comm.message import Message

# handler: (service, msg) -> reply Message or None
Handler = Callable[[str, Message], Message | None]


class TransportError(Exception):
    """Peer unreachable / connection failed — the caller decides whether to
    fail over (the reference's primary→standby retry, `:956-963`).

    ``reason`` types the failure so the retry layer (comm/retry.py) can
    distinguish retryable transport faults from fatal protocol rejections:

    - ``timeout``      — no answer in time (peer may have processed it)
    - ``refused``      — connection refused (peer down / port closed)
    - ``closed``       — peer closed mid-exchange
    - ``unreachable``  — no route / address failure
    - ``stale_epoch``  — fenced by a higher coordinator epoch (never
      retryable; see membership/epoch.py)
    """

    RETRYABLE = frozenset({"timeout", "refused", "closed", "unreachable"})

    def __init__(self, message: str = "",
                 reason: str = "unreachable") -> None:
        super().__init__(message)
        self.reason = reason

    @property
    def retryable(self) -> bool:
        return self.reason in self.RETRYABLE


class Transport(abc.ABC):
    """One node's endpoint: serve handlers, call peers."""

    # optional differential-health feed (membership/health.py): when a
    # HealthLedger is attached here, every reliable call's latency and
    # error observation lands in it. None = no observation (default; the
    # chaos harness only attaches it under the fail-slow flag so seeded
    # schedules without it burn no extra state).
    health = None

    @abc.abstractmethod
    def serve(self, service: str, handler: Handler) -> None:
        """Register the handler for a named service on this node."""

    @abc.abstractmethod
    def call(self, host: str, service: str, msg: Message,
             timeout: float | None = None) -> Message | None:
        """Reliable request/response (the TCP paths). Raises TransportError
        if the peer is unreachable."""

    @abc.abstractmethod
    def datagram(self, host: str, service: str, msg: Message) -> None:
        """Unreliable fire-and-forget (the UDP membership path). Silently
        drops if the peer is unreachable."""

    @abc.abstractmethod
    def close(self) -> None:
        ...
