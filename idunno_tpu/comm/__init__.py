from idunno_tpu.comm.message import Message  # noqa: F401
from idunno_tpu.comm.transport import Transport  # noqa: F401
from idunno_tpu.comm.inproc import InProcNetwork, InProcTransport  # noqa: F401
