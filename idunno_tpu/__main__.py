"""Run one cluster node with the interactive shell.

    python -m idunno_tpu --host node0 [--config cluster.json] \
        [--data-dir ./node0-data] [--dataset ./images] [--no-shell]

The config JSON mirrors ``ClusterConfig`` (hosts, coordinator,
standby_coordinator, introducer, ports, timeouts); an ``addresses`` map
{host: ip} may be included for multi-machine deployments — otherwise all
hosts resolve to 127.0.0.1 with per-host port offsets (single-machine
clusters), replacing the reference's hardcoded IP tables (`utils.py:70-92`).
"""
from __future__ import annotations

import argparse
import json
import sys


def build_addr_of(config, addresses: dict[str, str]):
    def addr_of(host: str):
        ip = addresses.get(host, "127.0.0.1")
        # distinct ports per host when everything is local
        offset = (0 if addresses.get(host) else
                  100 * config.hosts.index(host))
        return (ip, config.ports.store + offset,
                config.ports.membership + offset)
    return addr_of


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="idunno_tpu")
    ap.add_argument("--host", required=True, help="this node's name")
    ap.add_argument("--config", help="cluster config JSON")
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--dataset", default=None,
                    help="local dataset root (test_<N>.JPEG files)")
    ap.add_argument("--no-shell", action="store_true",
                    help="run headless (no interactive shell)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the engine onto CPU (ops testing; several "
                         "local nodes can't share one TPU chip)")
    ap.add_argument("--jax-coordinator", default=None,
                    help="ip:port for jax.distributed bring-up (multi-host "
                         "mesh over DCN); all nodes must pass the same value")
    ap.add_argument("--jax-num-processes", type=int, default=None)
    ap.add_argument("--jax-process-id", type=int, default=None)
    args = ap.parse_args(argv)

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from idunno_tpu.utils.compile_cache import enable_persistent_cache
    enable_persistent_cache()

    if args.jax_coordinator:
        from idunno_tpu.parallel.mesh import initialize_distributed
        initialize_distributed(args.jax_coordinator,
                               num_processes=args.jax_num_processes,
                               process_id=args.jax_process_id)

    from idunno_tpu.cli.shell import Shell
    from idunno_tpu.comm.net import NetTransport
    from idunno_tpu.config import ClusterConfig
    from idunno_tpu.serve.node import Node

    addresses: dict[str, str] = {}
    engine_config = None
    if args.config:
        with open(args.config) as f:
            raw = json.load(f)
        addresses = raw.pop("addresses", {})
        engine_raw = raw.pop("engine", None)
        if engine_raw is not None:
            from idunno_tpu.config import EngineConfig
            engine_config = EngineConfig(**engine_raw)
        if "ports" in raw:
            from idunno_tpu.config import PortConfig
            raw["ports"] = PortConfig(**raw["ports"])
        if "hosts" in raw:
            raw["hosts"] = tuple(raw["hosts"])
        config = ClusterConfig(**raw)
    else:
        config = ClusterConfig.from_env()
    if args.host not in config.hosts:
        ap.error(f"--host {args.host!r} not in configured hosts")

    transport = NetTransport(args.host, build_addr_of(config, addresses))
    node = Node(args.host, config, transport,
                data_dir=args.data_dir or f"./{args.host}-data",
                engine_config=engine_config,
                dataset_root=args.dataset)
    node.start()
    try:
        if args.no_shell:
            import threading
            threading.Event().wait()
        else:
            Shell(node).run()
    except KeyboardInterrupt:
        pass
    finally:
        node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
