"""Shared sampling transforms for the generation tiers.

One implementation of top-k and nucleus (top-p) filtering serves both
one-shot `engine.generate` and the continuous-batching pool /
speculative-sampling path (`engine.serve_lm`) — the pool's
distribution-exactness contract depends on the two tiers filtering
identically, so the construction lives here once. Two forms of it:

- `sample_keep_mask`/`masked_sample_logits`: the TOKEN-exact hot path
  (generate loop, `fused_decode_tail`, the prefill pick). Thresholds
  come from exact bit-bisection over f32 patterns, so the whole tail is
  elementwise ops + per-row reductions — GSPMD partitions it over a
  vocab-sharded unembed without all-gathering [rows, vocab] logits
  (ISSUE 16).
- `filtered_probs`/`nucleus_probs`: the sort-based NORMALIZED
  distribution, kept for speculative verification (`spec_commit` needs
  actual probabilities, and the spec contract is distribution-exact,
  not stream-exact).

Reference has no sampling at all (`alexnet_resnet.py` serves argmax
classifications only).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _nucleus_on_probs(probs: jnp.ndarray,
                      top_p: jnp.ndarray) -> jnp.ndarray:
    """Nucleus-filter an (already normalized) probability tensor over the
    LAST axis. The nucleus is the smallest sorted-probability prefix whose
    mass reaches top_p, with the target clamped to the achievable float32
    cumsum total so round-off near 1.0 can't collapse the nucleus to the
    argmax token. top_p >= 1 is the identity."""
    sorted_p = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    target = jnp.minimum(top_p[..., None], cum[..., -1:])
    k_idx = jnp.argmax(cum >= target, axis=-1)
    cutoff = jnp.take_along_axis(sorted_p, k_idx[..., None], axis=-1)
    keep = (probs >= cutoff) | (top_p[..., None] >= 1.0)
    filt = jnp.where(keep, probs, 0.0)
    return filt / filt.sum(axis=-1, keepdims=True)


def nucleus_probs(scaled_logits: jnp.ndarray,
                  top_p: jnp.ndarray) -> jnp.ndarray:
    """Temperature-scaled logits → nucleus-filtered, renormalized
    probabilities over the LAST axis (any leading shape; ``top_p``
    broadcasts over it)."""
    return _nucleus_on_probs(jax.nn.softmax(scaled_logits, axis=-1), top_p)


def filtered_probs(scaled_logits: jnp.ndarray, top_p: jnp.ndarray,
                   top_k: jnp.ndarray) -> jnp.ndarray:
    """Temperature-scaled logits → top-k then nucleus filtered,
    renormalized probabilities over the LAST axis.

    ``top_k`` is integer (0 or >= vocab disables the k-filter); ``top_p``
    as in `nucleus_probs`; both broadcast over the leading shape. Filter
    order matches the standard sequential-warper convention: the k
    largest tokens are kept first (ties AT the k-th probability are all
    kept — the filter is a probability threshold, so equal-probability
    tokens are indistinguishable), then the nucleus is taken over the
    RENORMALIZED top-k distribution. With both filters off this is the
    plain softmax."""
    probs = jax.nn.softmax(scaled_logits, axis=-1)
    v = probs.shape[-1]
    k = jnp.clip(top_k, 0, v)
    # ONE descending sort serves both filters (this runs on the decode
    # hot path): the top-k survivors are exactly the prefix of sorted_p
    # at/above the k-th probability, and k-masking preserves sort order,
    # so the nucleus cutoff over the RENORMALIZED top-k distribution is
    # derivable from the same sorted array — cumsum of the masked prefix
    # divided by its total is the normalized cumulative the nucleus
    # construction needs.
    sorted_p = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
    idx = jnp.clip(k - 1, 0, v - 1)
    kth = jnp.take_along_axis(
        sorted_p, jnp.broadcast_to(idx[..., None], probs.shape[:-1] + (1,)),
        axis=-1)
    k_off = (k[..., None] <= 0) | (k[..., None] >= v)
    keep_k = (probs >= kth) | k_off
    masked_sorted = jnp.where((sorted_p >= kth) | k_off, sorted_p, 0.0)
    z = masked_sorted.sum(axis=-1, keepdims=True)
    cum = jnp.cumsum(masked_sorted, axis=-1) / z
    target = jnp.minimum(top_p[..., None], cum[..., -1:])
    k_idx = jnp.argmax(cum >= target, axis=-1)
    cutoff = jnp.take_along_axis(masked_sorted, k_idx[..., None], axis=-1)
    keep = keep_k & ((probs >= cutoff) | (top_p[..., None] >= 1.0))
    filt = jnp.where(keep, probs, 0.0)
    return filt / filt.sum(axis=-1, keepdims=True)


# float32 1.0 bit pattern: the bisection space for values in [0, 1]
_ONE_BITS = 0x3F800000


def _largest_true_bits(pred, rows: tuple) -> jnp.ndarray:
    """Largest f32 ``t`` in [0, nextafter(1)] with ``pred(t)`` True, per
    row. Non-negative IEEE floats order like their int32 bit patterns,
    so an exact binary search over the bit space finds the exact float
    where a monotone (non-increasing) predicate flips — no sort, no
    cumsum, only the elementwise compares and small reductions ``pred``
    itself makes. 31 rounds cover the ~2^30-wide pattern range."""
    lo = jnp.zeros(rows, jnp.int32)
    hi = jnp.full(rows, _ONE_BITS + 1, jnp.int32)

    def body(_, lh):
        lo, hi = lh
        mid = (lo + hi) >> 1
        ok = pred(jax.lax.bitcast_convert_type(mid, jnp.float32))
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

    lo, hi = jax.lax.fori_loop(0, 31, body, (lo, hi))
    return jax.lax.bitcast_convert_type(lo, jnp.float32)


def sample_keep_mask(scaled: jnp.ndarray, top_p: jnp.ndarray,
                     top_k: jnp.ndarray) -> jnp.ndarray:
    """Top-k + nucleus survivor mask over the LAST axis, in the
    partition-friendly form the vocab-sharded tail needs (ISSUE 16).

    Selects the same set as ``filtered_probs(scaled, top_p, top_k) > 0``
    — the k largest tokens (ties AT the k-th value all kept), then the
    smallest prefix of the renormalized top-k mass reaching ``top_p``
    (ties at the cutoff kept; an unreachable target degrades to the
    achievable mass automatically) — but computes its two thresholds by
    exact bit-bisection (`_largest_true_bits`) on the unnormalized
    softmax numerator ``e = exp(scaled - max)``:

      k-th value   = largest t with  count(e >= t)          >= k
      nucleus cut  = largest t with  mass(kept & e >= t)    >= top_p·Z

    Everything is elementwise ops + per-row reductions, so GSPMD
    partitions it over a sharded vocab axis with one small collective
    per reduction — no sort, cumsum, or take_along_axis to force an
    all-gather of the ``[rows, vocab]`` tensor. Working on ``e`` (not
    the normalized probs) keeps every comparison input elementwise —
    bitwise identical across mesh shapes; only the mass sums carry
    reduction-order rounding. Both generation tiers (`engine.generate`
    and the serving tail) build their masks here, so cross-tier
    token-exactness is structural."""
    v = scaled.shape[-1]
    rows = scaled.shape[:-1]
    e = jnp.exp((scaled - jnp.max(scaled, axis=-1, keepdims=True))
                .astype(jnp.float32))
    k = jnp.clip(top_k, 0, v)
    k_off = (k <= 0) | (k >= v)
    kth = _largest_true_bits(
        lambda t: jnp.sum(e >= t[..., None], axis=-1) >= k, rows)
    keep_k = (e >= kth[..., None]) | k_off[..., None]
    masked = jnp.where(keep_k, e, 0.0)
    z = jnp.sum(masked, axis=-1)
    # the tiny floor makes top_p→0 keep the argmax tie-set (the mass
    # predicate must fail above the largest kept value, not everywhere)
    target = jnp.maximum(top_p * z, jnp.float32(1e-38))
    cut = _largest_true_bits(
        lambda t: jnp.sum(jnp.where(masked >= t[..., None], masked, 0.0),
                          axis=-1) >= target, rows)
    p_off = top_p >= 1.0
    return keep_k & ((e >= cut[..., None]) | p_off[..., None])


def masked_sample_logits(scaled: jnp.ndarray, top_p: jnp.ndarray,
                         top_k: jnp.ndarray) -> jnp.ndarray:
    """Per-row sampling logits in the MASKED-SCALED form: filtered rows
    keep their scaled logits on the survivor set and -inf elsewhere;
    filter-off rows pass through untouched. `jax.random.categorical` is
    shift-invariant per row, so drawing from these equals drawing from
    ``log(filtered_probs)`` — without normalizing over the (possibly
    vocab-sharded) axis. The per-ROW select keeps every row's formula a
    function of its own request alone (journal replays redraw the same
    stream without former co-residents)."""
    keep = sample_keep_mask(scaled, top_p, top_k)
    off = ~filter_on(top_p, top_k)
    return jnp.where(keep | off[..., None], scaled, -jnp.inf)


def safe_log(probs: jnp.ndarray) -> jnp.ndarray:
    """log with EXACT -inf outside the support — a filtered-out token
    must have probability zero, not e^-69 (matches generate's -inf
    nucleus masking)."""
    return jnp.where(probs > 0.0, jnp.log(jnp.maximum(probs, 1e-38)),
                     -jnp.inf)


def filter_on(top_p: jnp.ndarray, top_k: jnp.ndarray) -> jnp.ndarray:
    """Per-row: does this row ask for any sampling filter at all?"""
    return (top_p < 1.0) | (top_k > 0)


def fused_decode_tail(l_raw: jnp.ndarray, tokens: jnp.ndarray,
                      cursors: jnp.ndarray, remaining: jnp.ndarray,
                      temps: jnp.ndarray, top_ps: jnp.ndarray,
                      top_ks: jnp.ndarray, keys: jnp.ndarray,
                      logprobs: jnp.ndarray, pres: jnp.ndarray,
                      freq: jnp.ndarray, counts: jnp.ndarray, *,
                      max_len: int, eos_id: int | None, track: bool,
                      pen: bool) -> tuple:
    """The post-model tail of one continuous-batching decode step, fused
    into whatever jitted program calls it (`engine.serve_lm._build_decode`):
    penalties → temperature/top-k/top-p pick → token/logprob scatter →
    cursor/remaining/EOS bookkeeping → count update. ``l_raw`` is the raw
    [S, vocab] model logits for the step; returns ``(tokens, cursors,
    remaining, keys, logprobs, counts)``.

    The sampling machinery (per-row key split, temperature scale,
    log-softmax, gumbel draw) runs only when a LIVE row actually samples —
    an all-greedy pool (the common serving and bench case) skips the whole
    branch. Stream exactness: with any sampled live row the branch is the
    byte-identical math as always; without one, no row's output reads
    ``drawn`` (greedy picks argmax) and frozen keys are harmless (a
    retired sampled row never draws again; admission re-seeds the slot's
    key). ``track``/``pen``/``eos_id`` are compile-time flags — off means
    zero traced ops for that feature.

    Every op over the vocab axis is partition-friendly (ISSUE 16): the
    filter mask comes from `sample_keep_mask`, the draw/argmax are
    reductions GSPMD splits into shard-local stats + one small merge,
    the logprob pick is a one-hot sum and the count update an elementwise
    add — nothing sorts, cumsums, gathers, or scatters ``[S, vocab]``,
    so a vocab-sharded unembed (`parallel.sharding.lm_tp_specs`) flows
    through without an all-gather of the logits."""
    active = remaining > 0
    l = l_raw
    if pen:   # counts cover this row's GENERATED tokens only
        l = (l - pres[:, None] * (counts > 0)
             - freq[:, None] * counts.astype(l.dtype))

    def draw_sampled():
        # per-row key advance + sampled pick (row streams stay
        # independent of co-resident rows and of admissions)
        split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
        scaled = l / jnp.maximum(temps, 1e-6)[:, None]
        # the threshold bisections only run when some live row actually
        # asked for a filter; inside that branch the PER-ROW select in
        # `masked_sample_logits` passes unfiltered rows their untouched
        # scaled logits — identical to the other branch — so no row's
        # stream ever depends on its co-residents (token-exact journal
        # replay). categorical's shift-invariance makes the masked-scaled
        # form draw the same tokens `generate` draws from its own
        # identically-built mask.
        sample_logits = jax.lax.cond(
            jnp.any((remaining > 0) & (temps > 0.0)
                    & filter_on(top_ps, top_ks)),
            lambda: masked_sample_logits(scaled, top_ps, top_ks),
            lambda: scaled)
        d = jax.vmap(jax.random.categorical)(
            split[:, 0], sample_logits).astype(jnp.int32)
        return d, split[:, 1]

    drawn, keys = jax.lax.cond(
        jnp.any((remaining > 0) & (temps > 0.0)),
        draw_sampled,
        lambda: (jnp.zeros(tokens.shape[0], jnp.int32), keys))
    nxt = jnp.where(temps > 0.0, drawn,
                    jnp.argmax(l, axis=-1).astype(jnp.int32))
    wpos = jnp.clip(cursors + 1, 0, max_len - 1)
    old = jnp.take_along_axis(tokens, wpos[:, None], axis=1)[:, 0]
    rows = jnp.arange(tokens.shape[0])
    tokens = tokens.at[rows, wpos].set(jnp.where(active, nxt, old))
    if track:
        # logprobs report the RAW model distribution even on penalized
        # rows (sampler-independent semantics). Same float composition
        # as log_softmax + take_along_axis, but the pick is a one-hot
        # sum — summing one value against zeros is exact — so nothing
        # gathers over the vocab axis
        l32 = l_raw.astype(jnp.float32)
        shifted = l32 - jnp.max(l32, axis=-1, keepdims=True)
        lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1))
        iota = jnp.arange(l32.shape[-1])
        lp = jnp.sum(jnp.where(iota[None, :] == nxt[:, None],
                               shifted, 0.0), axis=-1) - lse
        lp_old = jnp.take_along_axis(logprobs, wpos[:, None], axis=1)[:, 0]
        logprobs = logprobs.at[rows, wpos].set(
            jnp.where(active, lp, lp_old))
    cursors = jnp.where(active, cursors + 1, cursors)
    new_remaining = remaining - 1
    if eos_id is not None:
        new_remaining = jnp.where(nxt == eos_id, 0, new_remaining)
    remaining = jnp.where(active, new_remaining, remaining)
    if pen:
        iota_v = jnp.arange(counts.shape[-1])
        hit = (iota_v[None, :] == nxt[:, None]) & active[:, None]
        counts = counts + hit.astype(counts.dtype)
    return tokens, cursors, remaining, keys, logprobs, counts
