"""Shared sampling transforms for the generation tiers.

One implementation of top-k and nucleus (top-p) filtering serves both
one-shot `engine.generate` and the continuous-batching pool /
speculative-sampling path (`engine.serve_lm`) — the pool's
distribution-exactness contract depends on the two tiers filtering
identically, so the construction lives here once. Reference has no
sampling at all (`alexnet_resnet.py` serves argmax classifications only).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _nucleus_on_probs(probs: jnp.ndarray,
                      top_p: jnp.ndarray) -> jnp.ndarray:
    """Nucleus-filter an (already normalized) probability tensor over the
    LAST axis. The nucleus is the smallest sorted-probability prefix whose
    mass reaches top_p, with the target clamped to the achievable float32
    cumsum total so round-off near 1.0 can't collapse the nucleus to the
    argmax token. top_p >= 1 is the identity."""
    sorted_p = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    target = jnp.minimum(top_p[..., None], cum[..., -1:])
    k_idx = jnp.argmax(cum >= target, axis=-1)
    cutoff = jnp.take_along_axis(sorted_p, k_idx[..., None], axis=-1)
    keep = (probs >= cutoff) | (top_p[..., None] >= 1.0)
    filt = jnp.where(keep, probs, 0.0)
    return filt / filt.sum(axis=-1, keepdims=True)


def nucleus_probs(scaled_logits: jnp.ndarray,
                  top_p: jnp.ndarray) -> jnp.ndarray:
    """Temperature-scaled logits → nucleus-filtered, renormalized
    probabilities over the LAST axis (any leading shape; ``top_p``
    broadcasts over it)."""
    return _nucleus_on_probs(jax.nn.softmax(scaled_logits, axis=-1), top_p)


def filtered_probs(scaled_logits: jnp.ndarray, top_p: jnp.ndarray,
                   top_k: jnp.ndarray) -> jnp.ndarray:
    """Temperature-scaled logits → top-k then nucleus filtered,
    renormalized probabilities over the LAST axis.

    ``top_k`` is integer (0 or >= vocab disables the k-filter); ``top_p``
    as in `nucleus_probs`; both broadcast over the leading shape. Filter
    order matches the standard sequential-warper convention: the k
    largest tokens are kept first (ties AT the k-th probability are all
    kept — the filter is a probability threshold, so equal-probability
    tokens are indistinguishable), then the nucleus is taken over the
    RENORMALIZED top-k distribution. With both filters off this is the
    plain softmax."""
    probs = jax.nn.softmax(scaled_logits, axis=-1)
    v = probs.shape[-1]
    k = jnp.clip(top_k, 0, v)
    # ONE descending sort serves both filters (this runs on the decode
    # hot path): the top-k survivors are exactly the prefix of sorted_p
    # at/above the k-th probability, and k-masking preserves sort order,
    # so the nucleus cutoff over the RENORMALIZED top-k distribution is
    # derivable from the same sorted array — cumsum of the masked prefix
    # divided by its total is the normalized cumulative the nucleus
    # construction needs.
    sorted_p = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
    idx = jnp.clip(k - 1, 0, v - 1)
    kth = jnp.take_along_axis(
        sorted_p, jnp.broadcast_to(idx[..., None], probs.shape[:-1] + (1,)),
        axis=-1)
    k_off = (k[..., None] <= 0) | (k[..., None] >= v)
    keep_k = (probs >= kth) | k_off
    masked_sorted = jnp.where((sorted_p >= kth) | k_off, sorted_p, 0.0)
    z = masked_sorted.sum(axis=-1, keepdims=True)
    cum = jnp.cumsum(masked_sorted, axis=-1) / z
    target = jnp.minimum(top_p[..., None], cum[..., -1:])
    k_idx = jnp.argmax(cum >= target, axis=-1)
    cutoff = jnp.take_along_axis(masked_sorted, k_idx[..., None], axis=-1)
    keep = keep_k & ((probs >= cutoff) | (top_p[..., None] >= 1.0))
    filt = jnp.where(keep, probs, 0.0)
    return filt / filt.sum(axis=-1, keepdims=True)
