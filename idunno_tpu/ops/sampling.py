"""Shared sampling transforms for the generation tiers.

One implementation of nucleus (top-p) filtering serves both one-shot
`engine.generate` and the continuous-batching pool / speculative-sampling
path (`engine.serve_lm`) — the pool's distribution-exactness contract
depends on the two tiers filtering identically, so the construction lives
here once. Reference has no sampling at all (`alexnet_resnet.py` serves
argmax classifications only).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def nucleus_probs(scaled_logits: jnp.ndarray,
                  top_p: jnp.ndarray) -> jnp.ndarray:
    """Temperature-scaled logits → nucleus-filtered, renormalized
    probabilities over the LAST axis (any leading shape; ``top_p``
    broadcasts over it). top_p >= 1 is the identity. The nucleus is the
    smallest sorted-probability prefix whose mass reaches top_p, with the
    target clamped to the achievable float32 cumsum total so round-off
    near 1.0 can't collapse the nucleus to the argmax token."""
    probs = jax.nn.softmax(scaled_logits, axis=-1)
    sorted_p = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    target = jnp.minimum(top_p[..., None], cum[..., -1:])
    k_idx = jnp.argmax(cum >= target, axis=-1)
    cutoff = jnp.take_along_axis(sorted_p, k_idx[..., None], axis=-1)
    keep = (probs >= cutoff) | (top_p[..., None] >= 1.0)
    filt = jnp.where(keep, probs, 0.0)
    return filt / filt.sum(axis=-1, keepdims=True)
