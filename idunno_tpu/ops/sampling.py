"""Shared sampling transforms for the generation tiers.

One implementation of top-k and nucleus (top-p) filtering serves both
one-shot `engine.generate` and the continuous-batching pool /
speculative-sampling path (`engine.serve_lm`) — the pool's
distribution-exactness contract depends on the two tiers filtering
identically, so the construction lives here once. Reference has no
sampling at all (`alexnet_resnet.py` serves argmax classifications only).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _nucleus_on_probs(probs: jnp.ndarray,
                      top_p: jnp.ndarray) -> jnp.ndarray:
    """Nucleus-filter an (already normalized) probability tensor over the
    LAST axis. The nucleus is the smallest sorted-probability prefix whose
    mass reaches top_p, with the target clamped to the achievable float32
    cumsum total so round-off near 1.0 can't collapse the nucleus to the
    argmax token. top_p >= 1 is the identity."""
    sorted_p = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    target = jnp.minimum(top_p[..., None], cum[..., -1:])
    k_idx = jnp.argmax(cum >= target, axis=-1)
    cutoff = jnp.take_along_axis(sorted_p, k_idx[..., None], axis=-1)
    keep = (probs >= cutoff) | (top_p[..., None] >= 1.0)
    filt = jnp.where(keep, probs, 0.0)
    return filt / filt.sum(axis=-1, keepdims=True)


def nucleus_probs(scaled_logits: jnp.ndarray,
                  top_p: jnp.ndarray) -> jnp.ndarray:
    """Temperature-scaled logits → nucleus-filtered, renormalized
    probabilities over the LAST axis (any leading shape; ``top_p``
    broadcasts over it)."""
    return _nucleus_on_probs(jax.nn.softmax(scaled_logits, axis=-1), top_p)


def filtered_probs(scaled_logits: jnp.ndarray, top_p: jnp.ndarray,
                   top_k: jnp.ndarray) -> jnp.ndarray:
    """Temperature-scaled logits → top-k then nucleus filtered,
    renormalized probabilities over the LAST axis.

    ``top_k`` is integer (0 or >= vocab disables the k-filter); ``top_p``
    as in `nucleus_probs`; both broadcast over the leading shape. Filter
    order matches the standard sequential-warper convention: the k
    largest tokens are kept first (ties AT the k-th probability are all
    kept — the filter is a probability threshold, so equal-probability
    tokens are indistinguishable), then the nucleus is taken over the
    RENORMALIZED top-k distribution. With both filters off this is the
    plain softmax."""
    probs = jax.nn.softmax(scaled_logits, axis=-1)
    v = probs.shape[-1]
    k = jnp.clip(top_k, 0, v)
    # ONE descending sort serves both filters (this runs on the decode
    # hot path): the top-k survivors are exactly the prefix of sorted_p
    # at/above the k-th probability, and k-masking preserves sort order,
    # so the nucleus cutoff over the RENORMALIZED top-k distribution is
    # derivable from the same sorted array — cumsum of the masked prefix
    # divided by its total is the normalized cumulative the nucleus
    # construction needs.
    sorted_p = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
    idx = jnp.clip(k - 1, 0, v - 1)
    kth = jnp.take_along_axis(
        sorted_p, jnp.broadcast_to(idx[..., None], probs.shape[:-1] + (1,)),
        axis=-1)
    k_off = (k[..., None] <= 0) | (k[..., None] >= v)
    keep_k = (probs >= kth) | k_off
    masked_sorted = jnp.where((sorted_p >= kth) | k_off, sorted_p, 0.0)
    z = masked_sorted.sum(axis=-1, keepdims=True)
    cum = jnp.cumsum(masked_sorted, axis=-1) / z
    target = jnp.minimum(top_p[..., None], cum[..., -1:])
    k_idx = jnp.argmax(cum >= target, axis=-1)
    cutoff = jnp.take_along_axis(masked_sorted, k_idx[..., None], axis=-1)
    keep = keep_k & ((probs >= cutoff) | (top_p[..., None] >= 1.0))
    filt = jnp.where(keep, probs, 0.0)
    return filt / filt.sum(axis=-1, keepdims=True)


def safe_log(probs: jnp.ndarray) -> jnp.ndarray:
    """log with EXACT -inf outside the support — a filtered-out token
    must have probability zero, not e^-69 (matches generate's -inf
    nucleus masking)."""
    return jnp.where(probs > 0.0, jnp.log(jnp.maximum(probs, 1e-38)),
                     -jnp.inf)


def filter_on(top_p: jnp.ndarray, top_k: jnp.ndarray) -> jnp.ndarray:
    """Per-row: does this row ask for any sampling filter at all?"""
    return (top_p < 1.0) | (top_k > 0)


def row_sample_logits(scaled: jnp.ndarray, top_p: jnp.ndarray,
                      top_k: jnp.ndarray) -> jnp.ndarray:
    """Per-row sampling logits: top-k/nucleus-filtered for rows that ask
    for a filter, plain log-softmax otherwise. The per-ROW select (not a
    batch-level branch) keeps every row's formula a function of its own
    request alone, so a journal replay without its former co-residents
    redraws the SAME stream bit-for-bit."""
    plain = jax.nn.log_softmax(scaled, axis=-1)
    filtered = safe_log(filtered_probs(scaled, top_p, top_k))
    return jnp.where(filter_on(top_p, top_k)[..., None], filtered, plain)


def fused_decode_tail(l_raw: jnp.ndarray, tokens: jnp.ndarray,
                      cursors: jnp.ndarray, remaining: jnp.ndarray,
                      temps: jnp.ndarray, top_ps: jnp.ndarray,
                      top_ks: jnp.ndarray, keys: jnp.ndarray,
                      logprobs: jnp.ndarray, pres: jnp.ndarray,
                      freq: jnp.ndarray, counts: jnp.ndarray, *,
                      max_len: int, eos_id: int | None, track: bool,
                      pen: bool) -> tuple:
    """The post-model tail of one continuous-batching decode step, fused
    into whatever jitted program calls it (`engine.serve_lm._build_decode`):
    penalties → temperature/top-k/top-p pick → token/logprob scatter →
    cursor/remaining/EOS bookkeeping → count update. ``l_raw`` is the raw
    [S, vocab] model logits for the step; returns ``(tokens, cursors,
    remaining, keys, logprobs, counts)``.

    The sampling machinery (per-row key split, temperature scale,
    log-softmax, gumbel draw) runs only when a LIVE row actually samples —
    an all-greedy pool (the common serving and bench case) skips the whole
    branch. Stream exactness: with any sampled live row the branch is the
    byte-identical math as always; without one, no row's output reads
    ``drawn`` (greedy picks argmax) and frozen keys are harmless (a
    retired sampled row never draws again; admission re-seeds the slot's
    key). ``track``/``pen``/``eos_id`` are compile-time flags — off means
    zero traced ops for that feature."""
    active = remaining > 0
    l = l_raw
    if pen:   # counts cover this row's GENERATED tokens only
        l = (l - pres[:, None] * (counts > 0)
             - freq[:, None] * counts.astype(l.dtype))

    def draw_sampled():
        # per-row key advance + sampled pick (row streams stay
        # independent of co-resident rows and of admissions)
        split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
        scaled = l / jnp.maximum(temps, 1e-6)[:, None]
        # the full-vocab sort+cumsum only runs when some live row
        # actually asked for a filter; inside that branch the PER-ROW
        # select gives unfiltered rows the identical plain log-softmax
        # the other branch computes, so no row's stream ever depends on
        # its co-residents (token-exact journal replay)
        sample_logits = jax.lax.cond(
            jnp.any((remaining > 0) & (temps > 0.0)
                    & filter_on(top_ps, top_ks)),
            lambda: row_sample_logits(scaled, top_ps, top_ks),
            lambda: jax.nn.log_softmax(scaled, axis=-1))
        d = jax.vmap(jax.random.categorical)(
            split[:, 0], sample_logits).astype(jnp.int32)
        return d, split[:, 1]

    drawn, keys = jax.lax.cond(
        jnp.any((remaining > 0) & (temps > 0.0)),
        draw_sampled,
        lambda: (jnp.zeros(tokens.shape[0], jnp.int32), keys))
    nxt = jnp.where(temps > 0.0, drawn,
                    jnp.argmax(l, axis=-1).astype(jnp.int32))
    wpos = jnp.clip(cursors + 1, 0, max_len - 1)
    old = jnp.take_along_axis(tokens, wpos[:, None], axis=1)[:, 0]
    rows = jnp.arange(tokens.shape[0])
    tokens = tokens.at[rows, wpos].set(jnp.where(active, nxt, old))
    if track:
        # logprobs report the RAW model distribution even on penalized
        # rows (sampler-independent semantics)
        lp_all = jax.nn.log_softmax(l_raw.astype(jnp.float32), axis=-1)
        lp = jnp.take_along_axis(lp_all, nxt[:, None], axis=1)[:, 0]
        lp_old = jnp.take_along_axis(logprobs, wpos[:, None], axis=1)[:, 0]
        logprobs = logprobs.at[rows, wpos].set(
            jnp.where(active, lp, lp_old))
    cursors = jnp.where(active, cursors + 1, cursors)
    new_remaining = remaining - 1
    if eos_id is not None:
        new_remaining = jnp.where(nxt == eos_id, 0, new_remaining)
    remaining = jnp.where(active, new_remaining, remaining)
    if pen:
        counts = counts.at[rows, nxt].add(jnp.where(active, 1, 0))
    return tokens, cursors, remaining, keys, logprobs, counts
