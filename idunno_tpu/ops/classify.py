"""Device-side classification heads.

The reference runs host-side ``softmax`` then ``topk(..., 1)`` per image
(`alexnet_resnet.py:80-88`). Here softmax + top-k happen on device over the
whole batch, so only (index, probability) pairs — not 1000-way probability
vectors — cross the HBM→host boundary.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def top1_from_logits(logits: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[B, C] logits → ([B] int32 class ids, [B] f32 probabilities)."""
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    top_prob = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
    return idx, top_prob


def topk_from_logits(logits: jnp.ndarray, k: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[B, C] logits → ([B, k] class ids, [B, k] probabilities)."""
    probs = jax.nn.softmax(logits, axis=-1)
    top_prob, idx = jax.lax.top_k(probs, k)
    return idx.astype(jnp.int32), top_prob
