"""Block-native paged decode attention (ISSUE 7 tentpole).

Queries attend over K/V blocks addressed *through the block table* —
no contiguous prefix is ever materialized (vLLM's PagedAttention,
PAPERS.md). Two interchangeable backends behind the same signature:

- ``kernel="pallas"``: a Pallas kernel whose grid walks the request's
  block chain; the block table rides in as a *scalar-prefetch* operand
  (`pltpu.PrefetchScalarGridSpec`) so the K/V BlockSpec index_map can
  address physical block ``tables[b, j]`` directly — the DMA engine
  does the "gather", one block at a time, overlapped with compute.
  Online softmax (running max/denominator) is structurally the same as
  `ops/flash_attention.py:_flash_kernel`, including the (rows, 128)
  broadcast-scratch trick for m/l and the `_out_struct` vma convention.
  ``interpret=True`` runs the same kernel on CPU for tier-1 tests.
- ``kernel="xla"``: stock-XLA fallback (gather + masked softmax) —
  the earn-it-or-swap baseline.

Both backends are int8-native (ISSUE 16): quantized pools hand their
per-token `k_scale`/`v_scale` leaves (`engine/kv_blocks.py:KV_LEAF_KEYS`,
``[N, bs, KVH]`` f32) through the same signature, and each backend
dequantizes its own tiles — the pallas kernel multiplies the scale
column into the block tile right after the int8→f32 cast, so no
dequantized copy of the pool ever materializes in HBM.

Both return *normalized* per-(query, kv-head, group) outputs plus the
log-sum-exp of their softmax, so the caller can merge with the
slot-local attention via `merge_attention` — exact because the merged
result is (o_a·Z_a + o_b·Z_b)/(Z_a+Z_b) with Z=exp(lse). A row with an
empty chain yields lse≈-1e30, whose merge weight underflows to exactly
0.0 in f32: zero-hit rows reproduce the dense result bit-for-bit.

Selection rule (CLAUDE.md conventions): ``resolve_paged_kernel`` maps
"auto" to the measured winner. Until the decode-shaped FLASH_SWEEP
section is captured on the real chip, "auto" stays on "xla"
(earn-it-or-swap: the kernel must beat the gather+flash baseline in
`FLASH_SWEEP.json` before it becomes the default).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from idunno_tpu.ops.flash_attention import _NEG_INF, _out_struct

# "auto" resolves here until the paged_suite capture blesses the kernel
# on the real chip (RESULTS.md staleness ledger tracks this).
AUTO_KERNEL = "xla"


def resolve_paged_kernel(kind: str, *, int8: bool = False) -> str:
    """Earn-it-or-swap selection: "auto" → measured winner ("xla" until
    the decode sweep says otherwise). Since ISSUE 16 the pallas kernel
    dequantizes int8 pages in-kernel, so ``int8`` no longer forces the
    xla path or refuses "pallas" — the kwarg stays for callers that
    still pass it, and "auto" resolves identically either way."""
    if kind not in ("auto", "pallas", "xla"):
        raise ValueError(f"paged_kernel must be auto|pallas|xla, got {kind!r}")
    del int8  # both backends are int8-native now
    if kind == "auto":
        return AUTO_KERNEL
    return kind


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PagedContext:
    """Everything the decode step needs to attend over paged KV.

    Traced children: per-layer (or stacked ``[L, ...]``) page stores,
    the per-row block table ``tables [S, C]`` (int32, dead entries 0)
    and block-aligned paged lengths ``lengths [S]``. Static aux:
    ``start`` (absolute cache position where the paged region begins —
    the static-prefix length), ``kernel`` and ``interpret``.
    """

    k_pages: Any
    v_pages: Any
    tables: Any
    lengths: Any
    k_scale_pages: Any = None
    v_scale_pages: Any = None
    start: int = 0
    kernel: str = "xla"
    interpret: bool = False

    def tree_flatten(self):
        children = (self.k_pages, self.v_pages, self.tables, self.lengths,
                    self.k_scale_pages, self.v_scale_pages)
        aux = (self.start, self.kernel, self.interpret)
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        kp, vp, tables, lengths, ks, vs = children
        start, kernel, interpret = aux
        return cls(k_pages=kp, v_pages=vp, tables=tables, lengths=lengths,
                   k_scale_pages=ks, v_scale_pages=vs, start=start,
                   kernel=kernel, interpret=interpret)

    def layer(self, kp, vp, ks=None, vs=None) -> "PagedContext":
        """Per-layer slice for the scanned decode body."""
        return dataclasses.replace(
            self, k_pages=kp, v_pages=vp,
            k_scale_pages=ks, v_scale_pages=vs)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _paged_kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref,
                  *refs, scale: float, block_size: int,
                  quantized: bool):
    """Grid (B, KVH, C), C innermost sequential: one program per
    (row, kv-head, chain position). The K/V BlockSpec index_map already
    resolved ``tables[b, j]`` — this body only decides liveness and
    runs one online-softmax step over the block.

    ``quantized=True`` threads two extra per-token scale tiles
    (``ks_ref``/``vs_ref``, one f32 scale per (token, kv-head)) into
    ``refs`` right before the outputs; dequant is the elementwise
    multiply into the int8→f32 cast below — the block never exists
    dequantized outside VMEM.

    No causal/position masking: the paged region wholly precedes the
    queries and ``lengths`` are block-aligned, so a live block is live
    in full. m/l live as (rows, 128) broadcast scratch (min-tile rule,
    same trick as `_flash_kernel`)."""
    if quantized:
        ks_ref, vs_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    else:
        ks_ref = vs_ref = None
        o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    b = pl.program_id(0)
    j = pl.program_id(2)
    nc = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(j * block_size < lengths_ref[b])
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)          # [rows, d]
        k = k_ref[0, :, 0].astype(jnp.float32)       # [bs, d]
        v = v_ref[0, :, 0].astype(jnp.float32)       # [bs, d]
        if quantized:
            k = k * ks_ref[0, :, 0].astype(jnp.float32)[:, None]
            v = v * vs_ref[0, :, 0].astype(jnp.float32)[:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [rows, bs]
        m_prev = m_ref[...].max(axis=-1, keepdims=True)   # [rows, 1]
        l_prev = l_ref[...].max(axis=-1, keepdims=True)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nc - 1)
    def _finalize():
        m = m_ref[...].max(axis=-1, keepdims=True)
        l = l_ref[...].max(axis=-1, keepdims=True)
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m + jnp.log(l_safe)).astype(lse_ref.dtype)


def _paged_pallas(q5, k_pages, v_pages, tables, lengths, *,
                  k_scale_pages=None, v_scale_pages=None,
                  scale: float, interpret: bool):
    """q5 [B,T,KVH,G,D] against pages [N,bs,KVH,D] via the block table.

    Rows = T*G query vectors per (batch, kv-head), padded to a multiple
    of 8 for the f32 min tile. The table is flattened and handed to the
    grid as a scalar-prefetch operand so the K/V index_map can read it.
    Quantized pools add two ``[N, bs, KVH]`` scale-page operands that
    ride the SAME index_map as their pages (one (bs, 1) scale column
    per program, the last-dim-1 block shape the lse out_spec already
    uses), so the dequant multiply happens in VMEM per block.
    """
    b, t, kvh, g, d = q5.shape
    n, bs, _, _ = k_pages.shape
    c = tables.shape[1]
    r = t * g
    rp = max(8, ((r + 7) // 8) * 8)
    qz = jnp.transpose(q5, (0, 2, 1, 3, 4)).reshape(b, kvh, r, d)
    if rp != r:
        qz = jnp.pad(qz, ((0, 0), (0, 0), (0, rp - r), (0, 0)))
    quantized = k_scale_pages is not None

    page_spec = pl.BlockSpec((1, bs, 1, d),
                             lambda bi, hi, ji, tbl, lens:
                             (tbl[bi * c + ji], 0, hi, 0))
    in_specs = [
        pl.BlockSpec((1, 1, rp, d),
                     lambda bi, hi, ji, tbl, lens: (bi, hi, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [qz, k_pages, v_pages]
    if quantized:
        scale_spec = pl.BlockSpec((1, bs, 1),
                                  lambda bi, hi, ji, tbl, lens:
                                  (tbl[bi * c + ji], 0, hi))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale_pages, v_scale_pages]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kvh, c),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, rp, d),
                         lambda bi, hi, ji, tbl, lens: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, rp, 1),
                         lambda bi, hi, ji, tbl, lens: (bi, hi, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((rp, d), jnp.float32),
            pltpu.VMEM((rp, 128), jnp.float32),
            pltpu.VMEM((rp, 128), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        functools.partial(_paged_kernel, scale=scale, block_size=bs,
                          quantized=quantized),
        grid_spec=grid_spec,
        out_shape=[
            _out_struct((b, kvh, rp, d), jnp.float32, q5),
            _out_struct((b, kvh, rp, 1), jnp.float32, q5),
        ],
        interpret=interpret,
    )(tables.reshape(-1), lengths, *operands)
    out = out[:, :, :r].reshape(b, kvh, t, g, d)
    lse = lse[:, :, :r, 0].reshape(b, kvh, t, g)
    return (jnp.transpose(out, (0, 2, 1, 3, 4)),
            jnp.transpose(lse, (0, 2, 1, 3)))


# ---------------------------------------------------------------------------
# Stock-XLA fallback (gather + masked softmax)
# ---------------------------------------------------------------------------

def _paged_xla(q5, k_pages, v_pages, tables, lengths, *,
               k_scale_pages=None, v_scale_pages=None, scale: float):
    b, t, kvh, g, d = q5.shape
    n, bs, _, _ = k_pages.shape
    c = tables.shape[1]
    k = k_pages[tables].astype(jnp.float32)   # [B,C,bs,KVH,D]
    v = v_pages[tables].astype(jnp.float32)
    if k_scale_pages is not None:
        k = k * k_scale_pages[tables].astype(jnp.float32)[..., None]
        v = v * v_scale_pages[tables].astype(jnp.float32)[..., None]
    k = jnp.transpose(k, (0, 3, 1, 2, 4)).reshape(b, kvh, c * bs, d)
    v = jnp.transpose(v, (0, 3, 1, 2, 4)).reshape(b, kvh, c * bs, d)
    q = jnp.transpose(q5, (0, 2, 1, 3, 4)).astype(jnp.float32)  # [B,KVH,T,G,D]
    s = jnp.einsum("bhtgd,bhsd->bhtgs", q, k) * scale
    live = (jnp.arange(c * bs)[None, :] < lengths[:, None])  # [B, C*bs]
    s = jnp.where(live[:, None, None, None, :], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = jnp.einsum("bhtgs,bhsd->bhtgd", p / l_safe, v)
    lse = (m + jnp.log(l_safe))[..., 0]
    # a fully-masked row degenerates to a uniform softmax over garbage;
    # the merge weight already underflows to 0 there, but pin the same
    # (zeros, _NEG_INF) contract the pallas kernel produces
    dead = lengths == 0
    o = jnp.where(dead[:, None, None, None, None], 0.0, o)
    lse = jnp.where(dead[:, None, None, None], _NEG_INF, lse)
    return (jnp.transpose(o, (0, 2, 1, 3, 4)),
            jnp.transpose(lse, (0, 2, 1, 3)))


# ---------------------------------------------------------------------------
# Public surface
# ---------------------------------------------------------------------------

def paged_attention_grouped(q5, k_pages, v_pages, tables, lengths, *,
                            k_scale_pages=None, v_scale_pages=None,
                            kernel: str = "xla", interpret: bool = False):
    """Grouped-query paged attention.

    q5 ``[B, T, KVH, G, D]`` (the transformer's head-grouping order:
    ``q.reshape(b, t, kv_heads, heads // kv_heads, d)``); pages
    ``[N, bs, KVH, D]``; tables ``[B, C]`` int32 (dead entries 0);
    lengths ``[B]`` int32 block-multiples. Returns normalized outputs
    ``[B, T, KVH, G, D]`` f32 and lse ``[B, T, KVH, G]`` f32 —
    lse≈-1e30 on empty chains (merge weight underflows to exactly 0).
    """
    d = q5.shape[-1]
    scale = 1.0 / (d ** 0.5)
    c = tables.shape[1]
    if c == 0:
        b, t, kvh, g, _ = q5.shape
        return (jnp.zeros((b, t, kvh, g, d), jnp.float32),
                jnp.full((b, t, kvh, g), _NEG_INF, jnp.float32))
    if kernel == "pallas":
        return _paged_pallas(q5, k_pages, v_pages, tables, lengths,
                             k_scale_pages=k_scale_pages,
                             v_scale_pages=v_scale_pages,
                             scale=scale, interpret=interpret)
    return _paged_xla(q5, k_pages, v_pages, tables, lengths,
                      k_scale_pages=k_scale_pages,
                      v_scale_pages=v_scale_pages, scale=scale)


@functools.partial(jax.jit, static_argnames=("kernel", "interpret"))
def paged_attention(q, k_pages, v_pages, tables, lengths, *,
                    k_scale_pages=None, v_scale_pages=None,
                    kernel: str = "xla", interpret: bool = False):
    """Flat-head convenience wrapper: q ``[B, T, H, D]`` → out
    ``[B, T, H, D]`` f32 + lse ``[B, T, H]``. H must be a multiple of
    the page store's KVH (standard GQA grouping)."""
    b, t, h, d = q.shape
    kvh = k_pages.shape[2]
    if h % kvh:
        raise ValueError(f"heads {h} not a multiple of kv_heads {kvh}")
    q5 = q.reshape(b, t, kvh, h // kvh, d)
    o5, lse5 = paged_attention_grouped(
        q5, k_pages, v_pages, tables, lengths,
        k_scale_pages=k_scale_pages, v_scale_pages=v_scale_pages,
        kernel=kernel, interpret=interpret)
    return o5.reshape(b, t, h, d), lse5.reshape(b, t, h)


def merge_attention(o_a, lse_a, o_b, lse_b):
    """Merge two normalized attention partials over disjoint key sets.

    Exact: with Z=exp(lse) the softmax over the union is
    (o_a·Z_a + o_b·Z_b)/(Z_a+Z_b). lse inputs broadcast against o with
    a trailing feature axis. An lse of ≈-1e30 contributes weight
    exactly 0.0 (f32 underflow), so an empty partial is a no-op."""
    m = jnp.maximum(lse_a, lse_b)
    wa = jnp.exp(lse_a - m)[..., None]
    wb = jnp.exp(lse_b - m)[..., None]
    return (o_a * wa + o_b * wb) / (wa + wb)
