"""Pallas TPU flash attention (blockwise online-softmax attention), with a
recompute-based backward pass — trainable end-to-end.

The memory-bound hot op of the transformer family: materializing the full
[T, T] score matrix costs O(T²) HBM traffic and VMEM; this kernel streams
K/V blocks through VMEM, keeping only a [block_q, D] accumulator plus the
online-softmax running max/denominator, so scores never leave the chip.
Same contract as `idunno_tpu.parallel.ring_attention.full_attention`
(q/k/v [B, T, H, D] → [B, T, H, D]) and plugs into
`idunno_tpu.models.transformer.TransformerLM` as ``attn_fn``, or into
Ulysses sequence parallelism as the per-shard local attention — ring
attention already achieves the same O(T²)-avoidance across chips; this
achieves it within a chip.

Differentiation: a `jax.custom_vjp` whose forward also emits the per-row
logsumexp; the backward never stores the [T, T] probability matrix —
two Pallas kernels recompute p = exp(s - lse) blockwise (the standard
FlashAttention backward):

    delta = rowsum(dO ∘ O)                       (XLA, [G, T])
    dQ    = Σ_k  [p ∘ (dO Vᵀ − delta)]·scale K   (kernel 1, scans k)
    dK    = Σ_q  [p ∘ (dO Vᵀ − delta)]ᵀ·scale Q  (kernel 2, scans q)
    dV    = Σ_q  pᵀ dO                           (kernel 2)

Grid: (batch·heads, q_blocks, k_blocks) — the innermost dimension is
sequential on TPU, so scratch accumulators carry across the scanned axis and
outputs are finalized on its last step. Causal masking skips fully-masked
blocks via ``pl.when`` (no wasted MXU work on the upper triangle) and
applies the intra-block triangle with a broadcasted-iota mask. T is padded
to a multiple of block_q (block_k falls back to block_q when it does not
divide the padded length) so grid coverage always equals the buffer (no
silently-skipped tail blocks).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30                  # safe -inf for masking (avoids inf-inf NaN)


def _masked_scores(q, k, iq, jk, *, scale, causal, block_q, block_k,
                   seq_len, t_pad):
    """[bq, D]x[bk, D] → masked f32 score block [bq, bk] (shared by the
    forward and both backward kernels — recompute must match bit-for-bit)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    k_pos = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    if causal:
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    if t_pad > seq_len:              # buffer padded: mask the padded keys
        s = jnp.where(k_pos < seq_len, s, _NEG_INF)
    return s


def _live(iq, jk, *, causal, block_q, block_k):
    """causal: block (iq, jk) is dead when its highest query position is
    strictly below its lowest key position."""
    return (iq * block_q + block_q - 1 >= jk * block_k) if causal else True


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                  *, scale: float, causal: bool, block_q: int, block_k: int,
                  seq_len: int, t_pad: int):
    iq, jk = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(_live(iq, jk, causal=causal, block_q=block_q, block_k=block_k))
    def _step():
        q = q_ref[0].astype(jnp.float32)                  # [bq, D]
        k = k_ref[0].astype(jnp.float32)                  # [bk, D]
        v = v_ref[0].astype(jnp.float32)
        s = _masked_scores(q, k, iq, jk, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           seq_len=seq_len, t_pad=t_pad)

        m_prev = m_ref[:].max(axis=-1, keepdims=True)     # [bq, 1] (bcast)
        l_prev = l_ref[:].max(axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                            # [bq, bk]
        l_new = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(jk == nk - 1)
    def _finalize():
        m = m_ref[:].max(axis=-1, keepdims=True)
        l = l_ref[:].max(axis=-1, keepdims=True)
        l_safe = jnp.where(l == 0.0, 1.0, l)              # fully-masked rows
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m + jnp.log(l_safe)                  # [bq, 1]


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, acc_ref, *, scale: float, causal: bool,
                         block_q: int, block_k: int, seq_len: int,
                         t_pad: int):
    iq, jk = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(_live(iq, jk, causal=causal, block_q=block_q, block_k=block_k))
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)                # [bq, D]
        s = _masked_scores(q, k, iq, jk, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           seq_len=seq_len, t_pad=t_pad)
        p = jnp.exp(s - lse_ref[0])   # [bq, bk]
        dp = jax.lax.dot_general(                          # dO·Vᵀ  [bq, bk]
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        acc_ref[:] = acc_ref[:] + jax.lax.dot_general(     # ds·K  [bq, D]
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jk == nk - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                          causal: bool, block_q: int, block_k: int,
                          seq_len: int, t_pad: int):
    jk, iq = pl.program_id(1), pl.program_id(2)   # k block fixed, scan q
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(_live(iq, jk, causal=causal, block_q=block_q, block_k=block_k))
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = _masked_scores(q, k, iq, jk, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           seq_len=seq_len, t_pad=t_pad)
        p = jnp.exp(s - lse_ref[0])   # [bq, bk]
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(       # pᵀ·dO  [bk, D]
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(       # dsᵀ·Q  [bk, D]
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _out_struct(shape, dtype, like):
    """ShapeDtypeStruct carrying the input's varying axes when running under
    shard_map (newer jax tracks vma on avals)."""
    try:
        vma = jax.typeof(like).vma
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except (AttributeError, TypeError):     # pragma: no cover - older jax
        return jax.ShapeDtypeStruct(shape, dtype)


def _flash_core(qb, kb, vb, causal, block_q, block_k, seq_len, interpret):
    """[G, T_pad, D]×3 → (out [G, T_pad, D], lse [G, T_pad])."""
    g, t_pad, d = qb.shape
    scale = 1.0 / (d ** 0.5)
    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               seq_len=seq_len, t_pad=t_pad)
    # LSE rides as [G, T_pad, 1]: a (1, block_q, 1) block is a legal TPU
    # tile — the trailing dim equals the array dim, and the middle dim is
    # either a multiple of 8 (block_q=256 default) or equal to t_pad
    # (ragged short sequences, where block_q == t == t_pad). The natural
    # (1, block_q) block over [G, T_pad] violates the (8, 128)
    # minimum-tile rule and fails to lower on real TPU (observed live:
    # BENCH_LAST_GOOD_lm.json 2026-07-31 capture).
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(_out_struct((g, t_pad, d), qb.dtype, qb),
                   _out_struct((g, t_pad, 1), jnp.float32, qb)),
        grid=(g, t_pad // block_q, t_pad // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, i, j: (g, j, 0)),
        ],
        out_specs=(pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0)),
                   pl.BlockSpec((1, block_q, 1), lambda g, i, j: (g, i, 0))),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),    # acc
                        pltpu.VMEM((block_q, 128), jnp.float32),  # running max
                        pltpu.VMEM((block_q, 128), jnp.float32)], # running sum
        interpret=interpret,
    )(qb, kb, vb)
    return out, lse[..., 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(qb, kb, vb, causal, block_q, block_k, seq_len, interpret):
    out, _ = _flash_core(qb, kb, vb, causal, block_q, block_k, seq_len,
                         interpret)
    return out


def _flash_fwd(qb, kb, vb, causal, block_q, block_k, seq_len, interpret):
    out, lse = _flash_core(qb, kb, vb, causal, block_q, block_k, seq_len,
                           interpret)
    return out, (qb, kb, vb, out, lse)


def _flash_bwd(causal, block_q, block_k, seq_len, interpret, res, do):
    qb, kb, vb, out, lse = res
    g, t_pad, d = qb.shape
    scale = 1.0 / (d ** 0.5)
    # delta = rowsum(dO ∘ O): cheap elementwise reduce, XLA fuses it.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                               # [G, T_pad]
    # Row vectors enter the kernels as [G, T_pad, 1] so their (1, block_q, 1)
    # blocks satisfy the TPU minimum-tile rule (see _flash_core).
    lse3, delta3 = lse[..., None], delta[..., None]
    nq, nk = t_pad // block_q, t_pad // block_k
    qspec = pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0))
    rowspec = pl.BlockSpec((1, block_q, 1), lambda g, i, j: (g, i, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          seq_len=seq_len, t_pad=t_pad),
        out_shape=_out_struct((g, t_pad, d), qb.dtype, qb),
        grid=(g, nq, nk),
        in_specs=[
            qspec,
            pl.BlockSpec((1, block_k, d), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, i, j: (g, j, 0)),
            qspec, rowspec, rowspec,
        ],
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, do, lse3, delta3)

    # dk/dv grid: k block is the carried (outer) axis, q is scanned last.
    kspec = pl.BlockSpec((1, block_k, d), lambda g, j, i: (g, j, 0))
    qspec2 = pl.BlockSpec((1, block_q, d), lambda g, j, i: (g, i, 0))
    rowspec2 = pl.BlockSpec((1, block_q, 1), lambda g, j, i: (g, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          seq_len=seq_len, t_pad=t_pad),
        out_shape=(_out_struct((g, t_pad, d), kb.dtype, kb),
                   _out_struct((g, t_pad, d), vb.dtype, vb)),
        grid=(g, nk, nq),
        in_specs=[qspec2, kspec, kspec, qspec2, rowspec2, rowspec2],
        out_specs=(kspec, kspec),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, do, lse3, delta3)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def resolve_blocks(t: int, block_q: int = 256,
                   block_k: int = 1024) -> tuple[int, int, int]:
    """The EFFECTIVE (block_q, block_k, t_pad) `flash_attention` will run
    for sequence length ``t`` — the single source of truth for block
    legality, exported so sweep tooling can label records with the
    geometry that actually executed (a request that cannot divide the
    padded length is lowered, never silently mislabeled)."""
    block_q = min(block_q, t)
    t_pad = -(-t // block_q) * block_q
    block_k = min(block_k, t_pad)
    if t_pad % block_k:
        # keep the effective block as close to the request as legality
        # allows: the largest multiple of 8 (TPU sublane tile) dividing
        # t_pad — e.g. t=1100 → t_pad=1280 → block_k 640, not a collapse
        # to block_q's 256. block_q always divides t_pad by construction,
        # so the final fallback is guaranteed legal.
        bk = (block_k // 8) * 8
        while bk >= 8 and t_pad % bk:
            bk -= 8
        block_k = bk if bk >= 8 else block_q
    return block_q, block_k, t_pad


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = False, block_q: int = 256,
                    block_k: int = 1024,
                    interpret: bool = False) -> jnp.ndarray:
    """q/k/v [B, T, H, D] → [B, T, H, D]. Ragged T is padded internally to a
    multiple of ``block_q`` (padded keys are masked, padded query rows are
    sliced off), so any sequence length works — e.g. ViT's n_patches+1;
    when ``block_k`` does not divide the padded length it is lowered to
    the largest multiple-of-8 divisor (a request that cannot run exactly
    as asked runs at the nearest legal geometry — re-sweeps should pick
    block sizes that divide the padded sequence to measure exactly what
    the label says). Differentiable: gradients flow through the
    recompute-based Pallas backward kernels above.

    Default blocks (256, 1024) are the measured winner of the on-chip
    sweep at batch 4 × seq 1024 on v5e (`tools/flash_sweep.py` →
    `FLASH_SWEEP.json`, 2026-08-01): 134.7k tok/s vs 99.8k at the old
    128×128 and 125.1k for stock XLA attention — tuned flash is the only
    configuration that beats XLA at these shapes."""
    b, t, h, d = q.shape
    block_q, block_k, t_pad = resolve_blocks(t, block_q, block_k)
    assert t_pad % block_q == 0 and t_pad % block_k == 0
    if t_pad != t:
        pad = [(0, 0), (0, t_pad - t), (0, 0), (0, 0)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))

    def bh(x):          # [B, T_pad, H, D] -> [B*H, T_pad, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, t_pad, d)

    out = _flash(bh(q), bh(k), bh(v), causal, block_q, block_k, t, interpret)
    return out.reshape(b, h, t_pad, d).transpose(0, 2, 1, 3)[:, :t]
