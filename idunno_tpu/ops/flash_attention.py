"""Pallas TPU flash attention (blockwise online-softmax attention).

The memory-bound hot op of the transformer family: materializing the full
[T, T] score matrix costs O(T²) HBM traffic and VMEM; this kernel streams
K/V blocks through VMEM, keeping only a [block_q, D] accumulator plus the
online-softmax running max/denominator, so scores never leave the chip.
Same contract as `idunno_tpu.parallel.ring_attention.full_attention`
(q/k/v [B, T, H, D] → [B, T, H, D]) and plugs into
`idunno_tpu.models.transformer.TransformerLM` as ``attn_fn``, or into
Ulysses sequence parallelism as the per-shard local attention — ring
attention already achieves the same O(T²)-avoidance across chips; this
achieves it within a chip.

Grid: (batch·heads, q_blocks, k_blocks); the innermost (k) dimension is
sequential on TPU, so the scratch accumulators carry across k steps and the
output block is finalized on the last one. Causal masking skips
fully-masked k blocks via ``pl.when`` (no wasted MXU work on the upper
triangle) and applies the intra-block triangle with a broadcasted-iota
mask.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30                  # safe -inf for masking (avoids inf-inf NaN)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  seq_len: int):
    iq, jk = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: block (iq, jk) is dead when its lowest query position is
    # strictly above its lowest key position's diagonal
    live = (iq * block_q + block_q - 1 >= jk * block_k) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)                  # [bq, D]
        k = k_ref[0].astype(jnp.float32)                  # [bk, D]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        if seq_len % block_k:            # ragged tail: mask padded keys
            s = jnp.where(k_pos < seq_len, s, _NEG_INF)

        m_prev = m_ref[:].max(axis=-1, keepdims=True)     # [bq, 1] (bcast)
        l_prev = l_ref[:].max(axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                            # [bq, bk]
        l_new = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(jk == nk - 1)
    def _finalize():
        l = l_ref[:].max(axis=-1, keepdims=True)
        l = jnp.where(l == 0.0, 1.0, l)                   # fully-masked rows
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = False, block_q: int = 128,
                    block_k: int = 128,
                    interpret: bool = False) -> jnp.ndarray:
    """q/k/v [B, T, H, D] → [B, T, H, D]. Ragged T is padded up to the
    block size internally (padded keys are masked, padded query rows are
    sliced off), so any sequence length works — e.g. ViT's n_patches+1."""
    b, t, h, d = q.shape
    block_q, block_k = min(block_q, t), min(block_k, t)
    t_pad = -(-t // block_q) * block_q
    t_pad = -(-t_pad // block_k) * block_k
    if t_pad != t:
        pad = [(0, 0), (0, t_pad - t), (0, 0), (0, 0)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))
    scale = 1.0 / (d ** 0.5)

    def bh(x):          # [B, T_pad, H, D] -> [B*H, T_pad, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, t_pad, d)

    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, seq_len=t)
    scratch = [pltpu.VMEM((block_q, d), jnp.float32),    # acc
               pltpu.VMEM((block_q, 128), jnp.float32),  # running max
               pltpu.VMEM((block_q, 128), jnp.float32)]  # running denom

    try:        # under shard_map the out aval must carry the varying axes
        vma = jax.typeof(q).vma
        out_shape = jax.ShapeDtypeStruct((b * h, t_pad, d), q.dtype, vma=vma)
    except (AttributeError, TypeError):     # pragma: no cover - older jax
        out_shape = jax.ShapeDtypeStruct((b * h, t_pad, d), q.dtype)

    out = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(b * h, t_pad // block_q, t_pad // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, i, j: (g, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0)),
        scratch_shapes=scratch,
        interpret=interpret,
    )(bh(q), bh(k), bh(v))
    return out.reshape(b, h, t_pad, d).transpose(0, 2, 1, 3)[:, :t]
