"""Weight-only int8 quantization for serving (beyond-parity capability).

TPU rationale: serving is usually HBM-bound on weights — every decode step
re-reads the full parameter set, and CNN serving re-reads it per batch.
Symmetric per-output-channel int8 halves (vs bf16) or quarters (vs f32) the
resident bytes; the dequantize (one multiply by a per-channel scale) happens
INSIDE the jitted forward, so XLA keeps the int8 tensors in HBM and fuses
the cast into the consumers. Weight-only means no activation calibration is
needed and the math error is bounded by half a quantization step per
channel (tested in `tests/test_quantize.py`).

The reference has no quantization story at all (weights are whatever
torch.hub shipped, reloaded per task — `alexnet_resnet.py:17-22`).

Representation: a params-shaped pytree where each quantized leaf is a
`QTensor` (int8 values + f32 per-channel scale, a registered pytree node)
and every other leaf (biases, norm scales — anything with ndim ≤ 1) stays
untouched; pass a custom ``should_quantize`` to exempt more (e.g. keep
embeddings full precision). `dequantize_tree` restores a plain params tree
— `module.apply` sees exactly the structure it was trained with.
"""
from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp


@flax.struct.dataclass
class QTensor:
    """Symmetric int8 weight + per-output-channel (last axis) f32 scale."""

    q: jnp.ndarray          # int8, same shape as the original weight
    scale: jnp.ndarray      # f32, shape (..broadcast.., out_channels)

    @property
    def shape(self):
        return self.q.shape


def _is_qtensor(x: Any) -> bool:
    return isinstance(x, QTensor)


def quantize_leaf(w: jnp.ndarray) -> QTensor:
    """Symmetric per-last-axis-channel int8: scale = max|w| / 127 per
    channel (zero channels get scale 1 to avoid 0/0)."""
    absmax = jnp.max(jnp.abs(w), axis=tuple(range(w.ndim - 1)),
                     keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return QTensor(q=q.astype(jnp.int8), scale=scale)


def default_should_quantize(path, leaf) -> bool:
    """Quantize matmul/conv kernels: float leaves with ndim ≥ 2 (Dense
    [in, out], DenseGeneral [.., h, d], Conv [kh, kw, cin, cout], Embed
    [vocab, dim]); biases/norm scales (ndim ≤ 1) stay full precision."""
    return (hasattr(leaf, "ndim") and leaf.ndim >= 2
            and jnp.issubdtype(leaf.dtype, jnp.floating))


def quantize_tree(params: Any, should_quantize=default_should_quantize) -> Any:
    """params tree → same-structure tree with `QTensor` at quantized leaves."""
    def f(path, leaf):
        if should_quantize(path, leaf):
            return quantize_leaf(jnp.asarray(leaf))
        return leaf
    return jax.tree_util.tree_map_with_path(f, params)


def dequantize_tree(qparams: Any, dtype=None) -> Any:
    """Inverse: QTensor leaves → dense arrays (jit-traceable; call INSIDE
    the jitted forward so int8 stays resident and the cast fuses)."""
    def f(leaf):
        if _is_qtensor(leaf):
            w = leaf.q.astype(jnp.float32) * leaf.scale
            return w.astype(dtype) if dtype is not None else w
        return leaf
    return jax.tree.map(f, qparams, is_leaf=_is_qtensor)


def quantized_bytes(qparams: Any) -> tuple[int, int]:
    """(bytes as stored, bytes if dense f32) — the HBM win, for logs/stats."""
    stored = dense = 0
    for leaf in jax.tree.leaves(qparams, is_leaf=_is_qtensor):
        if _is_qtensor(leaf):
            stored += leaf.q.size + 4 * leaf.scale.size
            dense += 4 * leaf.q.size
        else:
            stored += leaf.size * leaf.dtype.itemsize
            dense += leaf.size * leaf.dtype.itemsize
    return stored, dense
