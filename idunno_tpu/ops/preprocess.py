"""Device-side image preprocessing.

The reference preprocesses one image at a time on the host with torchvision
transforms — Resize(256) / CenterCrop(224) / ToTensor / Normalize
(`alexnet_resnet.py:57-62`). Here the host loader only decodes and resizes to
a canonical static 256x256 (see `idunno_tpu.engine.data`); the crop, dtype
conversion, and normalization run on the TPU, batched and fused by XLA into
the first convolution's input pipeline. Static shapes throughout — one
compiled executable per (model, batch) pair, reused forever.
"""
from __future__ import annotations

import jax.numpy as jnp

# torchvision ImageNet normalization constants (`alexnet_resnet.py:61`).
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def center_crop(x: jnp.ndarray, size: int) -> jnp.ndarray:
    """Center-crop NHWC batch to ``size`` (static slice — jit friendly)."""
    h, w = x.shape[1], x.shape[2]
    top = (h - size) // 2
    left = (w - size) // 2
    return x[:, top:top + size, left:left + size, :]


def preprocess_batch(images_u8: jnp.ndarray, *, crop: int = 224) -> jnp.ndarray:
    """uint8 NHWC batch (canonical 256x256) → normalized f32 NHWC ``crop``².

    Matches CenterCrop(224) + ToTensor + Normalize from the reference
    pipeline; the Resize(256-shortest-side) half happens at decode time on
    the host.
    """
    x = center_crop(images_u8, crop)
    x = x.astype(jnp.float32) / 255.0
    mean = jnp.asarray(IMAGENET_MEAN, dtype=jnp.float32)
    std = jnp.asarray(IMAGENET_STD, dtype=jnp.float32)
    return (x - mean) / std
