"""Pallas TPU kernel: fused uint8 → normalized bfloat16 preprocessing.

The preprocess step (`idunno_tpu.ops.preprocess.preprocess_batch`) is pure
HBM bandwidth: read uint8 pixels once, write normalized bf16 once. This
kernel performs the cast + scale + per-channel mean/std in a single VMEM
pass over a [rows, W*C] view of the cropped image batch, with the channel
index recovered as ``lane % 3`` via a 2-D broadcasted iota (TPU needs ≥2-D
iota). The XLA path (`preprocess_batch`) produces identical values; the
engine (``InferenceEngine._use_pallas``) selects this kernel on TPU (or when
``EngineConfig.preprocess == "pallas"``) and the XLA path elsewhere.

Run on CPU with ``interpret=True`` (tests); compiled on TPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from idunno_tpu.ops.preprocess import IMAGENET_MEAN, IMAGENET_STD, center_crop

_ROWS_PER_BLOCK = 256


def _norm_kernel(x_ref, mean_ref, inv_std_ref, o_ref):
    # Mosaic has no direct u8->f32 cast; hop through int32.
    x = x_ref[:].astype(jnp.int32).astype(jnp.float32) * (1.0 / 255.0)
    lanes = jax.lax.broadcasted_iota(jnp.int32, x.shape, dimension=1)
    c = lanes % 3
    mean = jnp.where(c == 0, mean_ref[0, 0],
                     jnp.where(c == 1, mean_ref[0, 1], mean_ref[0, 2]))
    inv_std = jnp.where(c == 0, inv_std_ref[0, 0],
                        jnp.where(c == 1, inv_std_ref[0, 1],
                                  inv_std_ref[0, 2]))
    o_ref[:] = ((x - mean) * inv_std).astype(jnp.bfloat16)


@functools.partial(jax.jit, static_argnames=("crop", "interpret"))
def preprocess_batch_pallas(images_u8: jnp.ndarray, *, crop: int = 224,
                            interpret: bool = False) -> jnp.ndarray:
    """uint8 NHWC (canonical 256²) → normalized bf16 [B, crop, crop, 3]."""
    x = center_crop(images_u8, crop)            # XLA slice, fused upstream
    b, h, w, ch = x.shape
    rows = b * h
    flat = x.reshape(rows, w * ch)
    mean = jnp.asarray([IMAGENET_MEAN], dtype=jnp.float32)          # [1, 3]
    inv_std = 1.0 / jnp.asarray([IMAGENET_STD], dtype=jnp.float32)  # [1, 3]

    # carry the input's varying mesh axes on the out aval so the kernel can
    # run inside shard_map with check_vma on (newer jax tracks vma)
    try:
        out_shape = jax.ShapeDtypeStruct((rows, w * ch), jnp.bfloat16,
                                         vma=jax.typeof(flat).vma)
    except (AttributeError, TypeError):      # pragma: no cover - older jax
        out_shape = jax.ShapeDtypeStruct((rows, w * ch), jnp.bfloat16)

    block_rows = min(_ROWS_PER_BLOCK, rows)
    grid = (pl.cdiv(rows, block_rows),)
    out = pl.pallas_call(
        _norm_kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, w * ch), lambda i: (i, 0)),
            pl.BlockSpec((1, 3), lambda i: (0, 0)),
            pl.BlockSpec((1, 3), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, w * ch), lambda i: (i, 0)),
        interpret=interpret,
    )(flat, mean, inv_std)
    return out.reshape(b, h, w, ch)
