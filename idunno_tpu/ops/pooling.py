"""Adaptive average pooling with torch semantics (static shapes).

torchvision AlexNet uses ``AdaptiveAvgPool2d((6, 6))`` before the classifier.
Window boundaries follow torch: start = floor(i*H/out), end = ceil((i+1)*H/out).
The double loop is over the *output* grid (static, e.g. 36 cells), so XLA sees
a fixed fusion-friendly graph — no dynamic shapes.
"""
from __future__ import annotations

import jax.numpy as jnp


def adaptive_avg_pool(x: jnp.ndarray, out_hw: tuple[int, int]) -> jnp.ndarray:
    """NHWC [B,H,W,C] → [B,out_h,out_w,C]."""
    _, h, w, _ = x.shape
    out_h, out_w = out_hw
    if (h, w) == (out_h, out_w):
        return x
    rows = []
    for i in range(out_h):
        h0, h1 = (i * h) // out_h, -((-(i + 1) * h) // out_h)
        cols = []
        for j in range(out_w):
            w0, w1 = (j * w) // out_w, -((-(j + 1) * w) // out_w)
            cols.append(jnp.mean(x[:, h0:h1, w0:w1, :], axis=(1, 2)))
        rows.append(jnp.stack(cols, axis=1))
    return jnp.stack(rows, axis=1)
