from idunno_tpu.ops.preprocess import (  # noqa: F401
    IMAGENET_MEAN, IMAGENET_STD, center_crop, preprocess_batch)
from idunno_tpu.ops.classify import top1_from_logits, topk_from_logits  # noqa: F401
