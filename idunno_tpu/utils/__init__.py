from idunno_tpu.utils.types import MemberStatus, MessageType  # noqa: F401
from idunno_tpu.utils.ring import hash_ring_index, ring_order  # noqa: F401
