from idunno_tpu.utils.types import MemberStatus, MessageType  # noqa: F401
from idunno_tpu.utils.ring import file_replica_hosts, hash_ring_index  # noqa: F401
