"""Training-path hardware bench: LM and CNN train-step throughput.

The reference is inference-only (`alexnet_resnet.py` is its whole model
layer); training is one of this framework's beyond-parity capabilities
(PARITY.md "Beyond-parity"), and like the LM serving tier it needs its own
measured hardware surface, not just CPU-mesh correctness tests:

  lm      — `engine/train_lm.py` step on a `TransformerLM`: next-token CE
            forward + backward + adamw update as ONE jitted computation,
            batch sharded over the mesh data axis. On TPU the attention is
            the REAL Pallas flash kernel fwd+bwd (``interpret=False`` —
            a kernel that fails to compile raises; no silent fallback).
            Reported as trained tokens/sec with train MFU on the standard
            6·params-FLOPs-per-token convention (fwd 2N + bwd 4N) plus the
            attention quadratic term.
  accum   — the same step with gradient accumulation (``accum_steps=2``):
            the memory/throughput trade measured, not assumed.
  fsdp    — params + optimizer state sharded over the data axis
            (`engine/train.py:fsdp_shard_train_state`, ZeRO-3 layout);
            only meaningful when the mesh has >1 device on the data axis,
            so the single-chip TPU run skips it and the CPU-mesh tests
            cover it.
  cnn     — `engine/train.py` step on ResNet-18 (the reference's model
            family): images/sec with train MFU at 3× the analytic forward
            FLOPs (the caller passes the forward number so the MFU
            denominator stays pinned to `bench.py`'s unit-tested
            functions).

Every knob is env-overridable (BENCH_TRAIN_*); `bench.py` serves the suite
as ``BENCH_SUITE=train`` with the same one-JSON-line + last-good-cache
contract as the CNN and LM suites.
"""
from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, str(default)))


def train_bench_config(platform: str) -> dict:
    """Workload sizing; TPU gets a ~0.2 B-param LM + batch-256 ResNet-18,
    other platforms a smoke-test miniature (the CPU path proves the
    machinery, not numbers)."""
    tpu = platform == "tpu"
    return {
        "dim": _env_int("BENCH_TRAIN_DIM", 1024 if tpu else 64),
        "depth": _env_int("BENCH_TRAIN_DEPTH", 12 if tpu else 1),
        "heads": _env_int("BENCH_TRAIN_HEADS", 16 if tpu else 2),
        "vocab": _env_int("BENCH_TRAIN_VOCAB", 32768 if tpu else 128),
        "seq": _env_int("BENCH_TRAIN_SEQ", 1024 if tpu else 32),
        "batch": _env_int("BENCH_TRAIN_BATCH", 8),
        "iters": _env_int("BENCH_TRAIN_ITERS", 3),
        "cnn_batch": _env_int("BENCH_TRAIN_CNN_BATCH", 256 if tpu else 8),
        "cnn_image": _env_int("BENCH_TRAIN_CNN_IMAGE", 224 if tpu else 32),
    }


def _count_params(params) -> int:
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))


def _effective_flash_blocks(seq: int) -> str:
    """The geometry `flash_attention` will actually run at this sequence
    length (kernel defaults lowered through `resolve_blocks`) — derived,
    not hardcoded, so neither a default re-tune nor a non-default
    BENCH_TRAIN_SEQ can make this provenance field lie."""
    from idunno_tpu.ops.flash_attention import resolve_blocks
    bq, bk, _ = resolve_blocks(seq)
    return f"{bq}x{bk} (kernel default resolved at seq {seq})"


def _timed_steps(step_fn, state, args: tuple, iters: int,
                 trace_name: str | None = None):
    """Compile + sync on the first call, then ``iters`` timed steps (each
    synced by a D2H read of the loss — reliable through the tunnel where
    `block_until_ready` is not). Returns (median_s, compile_s, last_loss).
    With ``trace_name`` and BENCH_TRACE=1 one extra post-timing step runs
    under the profiler into ``.trace/<trace_name>`` (the apportionment
    evidence behind the train-MFU analysis; parse with
    tools/parse_trace.py)."""
    t0 = time.perf_counter()
    state, metrics = step_fn(state, *args)
    loss = float(np.asarray(metrics["loss"]))
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        state, metrics = step_fn(state, *args)
        loss = float(np.asarray(metrics["loss"]))
        times.append(time.perf_counter() - t0)
    if trace_name and os.environ.get("BENCH_TRACE") == "1":
        from idunno_tpu.utils.tracing import trace
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        with trace(os.path.join(root, ".trace", trace_name)):
            _, m = step_fn(state, *args)
            float(np.asarray(m["loss"]))
    return float(np.median(times)), compile_s, loss


def run_train_bench(platform: str, device_kind: str, n_devices: int,
                    peak_bf16: float | None, *, deadline: float,
                    cnn_flops_per_image: float | None = None) -> dict:
    """One measured training record. ``deadline`` is a perf_counter() stamp
    after which optional phases (accum, fsdp, cnn) are skipped — each is a
    fresh compile through a slow tunnel; the core LM point always runs."""
    import optax

    from idunno_tpu.engine.train import (create_train_state, flat_tx,
                                         fsdp_shard_train_state,
                                         jit_train_step, shard_train_state)
    from idunno_tpu.engine.train_lm import (create_lm_train_state,
                                            jit_lm_train_step)
    from idunno_tpu.models.resnet import resnet18
    from idunno_tpu.models.transformer import TransformerLM, make_attn_fn
    from idunno_tpu.parallel.mesh import DATA_AXIS, local_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = train_bench_config(platform)
    mesh = local_mesh()
    n_data = mesh.shape[DATA_AXIS]
    batch = -(-cfg["batch"] // n_data) * n_data    # divisible over data axis
    out: dict = {"config": dict(cfg, batch=batch),
                 "platform": platform, "device_kind": device_kind,
                 "n_devices": n_devices}

    # -- LM train step (flash fwd+bwd on TPU; loud failure, no fallback) ---
    # mixed precision: f32 params/optimizer, bf16 compute — the standard
    # training layout (serving benches use bf16 residency instead).
    attn = make_attn_fn("flash" if platform == "tpu" else "full")
    model = TransformerLM(vocab=cfg["vocab"], dim=cfg["dim"],
                          depth=cfg["depth"], num_heads=cfg["heads"],
                          causal=True, attn_fn=attn,
                          dtype=jnp.bfloat16, param_dtype=jnp.float32)
    # init through a plain-attention twin (identical param structure) at a
    # tiny seq — skips one expensive full-seq flash compile on the tunnel
    init_model = TransformerLM(vocab=cfg["vocab"], dim=cfg["dim"],
                               depth=cfg["depth"], num_heads=cfg["heads"],
                               causal=True,
                               dtype=jnp.bfloat16, param_dtype=jnp.float32)
    # flat layout: the traced per-tensor adamw stream was ~55% of the
    # 2026-07-31 device step (TRACE_TRAIN_LM.json); engine/train.py:flat_tx
    tx = flat_tx(optax.adamw(3e-4))
    try:
        state = create_lm_train_state(init_model, jax.random.PRNGKey(0),
                                      8, tx, batch=1)
        n_params = _count_params(state.params)
        out["n_params"] = n_params
        state = shard_train_state(state, mesh)
        tokens = jax.device_put(
            jnp.ones((batch, cfg["seq"]), jnp.int32),
            NamedSharding(mesh, P(DATA_AXIS)))
        step = jit_lm_train_step(model, tx, mesh)
        per_step, compile_s, loss = _timed_steps(
            step, state, (tokens,), cfg["iters"],
            trace_name="train_lm" if platform == "tpu" else None)
        tok_s = batch * cfg["seq"] / per_step
        out["lm"] = {
            "tokens_per_s": round(tok_s, 1),
            "batch": batch, "seq": cfg["seq"],
            "step_s": round(per_step, 4), "compile_s": round(compile_s, 2),
            "loss": round(loss, 4),
            "attention": ("flash (pallas fwd+bwd, compiled)"
                          if platform == "tpu" else "full (xla)"),
            # records at/after this field measure the flat-optimizer
            # layout; its absence marks the per-tensor-adamw era (the
            # 2026-07-31 30,499 tok/s baseline)
            "optimizer_layout": "flat (optax.flatten(adamw))",
            # record the block geometry: the FLASH_SWEEP that picked the
            # current default measured the prefill FORWARD only, so a
            # train capture at new blocks must be comparable-by-record
            # against the 128x128-era 30,499 tok/s baseline
            "flash_blocks": _effective_flash_blocks(cfg["seq"])
                            if platform == "tpu" else None,
        }
        # fwd 2N + bwd 4N per token, plus the attention quadratic term
        # (fwd 4·T·d per layer per token, ×3 with backward)
        flops_tok = (6.0 * n_params
                     + 12.0 * cfg["seq"] * cfg["dim"] * cfg["depth"])
        out["lm"]["flops_per_token_gf"] = round(flops_tok / 1e9, 6)
        if peak_bf16:
            out["lm"]["mfu"] = round(tok_s * flops_tok / peak_bf16, 4)
    except Exception as e:  # noqa: BLE001 - must record, never fall back
        out["lm"] = {"error": f"{type(e).__name__}: {e}"}
        if platform == "tpu":
            out["flash_attention"] = "FAILED_TO_COMPILE"
        return out
    out["flash_attention"] = ("compiled" if platform == "tpu"
                              else "n/a (cpu)")

    # -- gradient accumulation point --------------------------------------
    if time.perf_counter() < deadline:
        try:
            step2 = jit_lm_train_step(model, tx, mesh, accum_steps=2)
            per2, c2, _ = _timed_steps(step2, state, (tokens,), cfg["iters"])
            out["accum"] = {
                "accum_steps": 2,
                "tokens_per_s": round(batch * cfg["seq"] / per2, 1),
                "vs_plain": round(per_step / per2, 2),
                "compile_s": round(c2, 2),
            }
        except Exception as e:  # noqa: BLE001
            out["accum"] = {"error": f"{type(e).__name__}: {e}"}

    # -- FSDP (ZeRO-3) point: only meaningful with >1 device on the data
    # axis (the single-chip TPU run skips it; CPU-mesh tests cover it).
    # PER-TENSOR optimizer on purpose: ZeRO-3's point is sharded opt
    # state, and a flat [N] leaf only shards when N divides the axis —
    # so this point keeps the layout tests/test_fsdp.py covers, pays its
    # own step compile, and stamps the record (engine/train.py:flat_tx) --
    if n_data > 1 and time.perf_counter() < deadline:
        try:
            tx_pt = optax.adamw(3e-4)
            # init through the plain-attention twin at tiny seq, same as
            # the main point — re-initing with the flash model at full seq
            # would pay exactly the compile the twin exists to avoid
            fstate = create_lm_train_state(init_model, jax.random.PRNGKey(0),
                                           8, tx_pt, batch=1)
            fstate = fsdp_shard_train_state(fstate, mesh)
            fstep = jit_lm_train_step(model, tx_pt, mesh)
            perf, cf, _ = _timed_steps(fstep, fstate, (tokens,),
                                       cfg["iters"])
            out["fsdp"] = {
                "tokens_per_s": round(batch * cfg["seq"] / perf, 1),
                "vs_plain": round(per_step / perf, 2),
                "compile_s": round(cf, 2),
                "optimizer_layout":
                    "per-tensor (ZeRO-3 shards opt-state leaves)",
                "note": "vs_plain's numerator is the FLAT-layout plain "
                        "step (the shipped default) — it folds the "
                        "per-tensor layout cost in with the sharding "
                        "cost, not a same-layout A/B",
            }
        except Exception as e:  # noqa: BLE001
            out["fsdp"] = {"error": f"{type(e).__name__}: {e}"}

    # -- CNN train step (the reference's model family) ---------------------
    if time.perf_counter() < deadline:
        try:
            cb = -(-cfg["cnn_batch"] // n_data) * n_data
            size = cfg["cnn_image"]
            cnn = resnet18()
            ctx = flat_tx(optax.sgd(0.1, momentum=0.9))
            # global-avg-pool makes param shapes size-independent: init at
            # 64px to keep the init compile cheap through the tunnel
            cstate = create_train_state(cnn, jax.random.PRNGKey(0),
                                        min(size, 64), ctx, batch=1)
            cstate = shard_train_state(cstate, mesh)
            bspec = NamedSharding(mesh, P(DATA_AXIS))
            images = jax.device_put(
                jnp.zeros((cb, size, size, 3), jnp.float32), bspec)
            labels = jax.device_put(jnp.zeros((cb,), jnp.int32), bspec)
            cstep = jit_train_step(cnn, ctx, mesh)
            perc, cc, closs = _timed_steps(
                cstep, cstate, (images, labels), cfg["iters"],
                trace_name="train_cnn" if platform == "tpu" else None)
            ips = cb / perc
            out["cnn"] = {
                "model": "resnet18", "images_per_s": round(ips, 1),
                "batch": cb, "image_size": size,
                "step_s": round(perc, 4), "compile_s": round(cc, 2),
                "loss": round(closs, 4),
                "optimizer_layout": "flat (optax.flatten(sgd+momentum))",
            }
            if peak_bf16 and cnn_flops_per_image:
                out["cnn"]["mfu"] = round(
                    ips * 3.0 * cnn_flops_per_image / peak_bf16, 4)
        except Exception as e:  # noqa: BLE001
            out["cnn"] = {"error": f"{type(e).__name__}: {e}"}

    return out
