"""Dapper-style request tracing: spans on the wire, ring buffers per node.

Always-on distributed tracing (Sigelman et al., "Dapper", 2010 — PAPERS.md)
riding the exact payload-stamp mechanism `membership/epoch.py` built for
epoch fences: a ``trace`` key (``[trace_id, parent_span_id]``) travels on
existing verb payloads next to the ``epoch`` stamp, each node records named
spans into a bounded in-memory ring buffer, and the ``trace`` control verb
(serve/control.py) collects a request's spans cluster-wide for the shell
waterfall and `tools/trace_export.py` (Chrome/Perfetto trace-event JSON).

Design rules, mirrored from the fence helpers:

- **Stamping is optional everywhere**: an unstamped payload (old client,
  pre-trace peer) records nothing and changes nothing — tracing can never
  fail a request.
- **Deterministic ids**: span ids are ``<node>:<seq>`` from a per-store
  counter and trace ids ``t:<node>:<seq>`` — no uuid/random, so the chaos
  harness (`idunno_tpu/chaos.py`) replays byte-identical traces from a
  seed, and two stores never collide because the node name is the prefix.
- **Injectable clock**: the store takes ``clock=`` exactly like
  `serve/metrics.py:MetricsTracker`, so fake-clock tests (gateway suite,
  chaos, TimedFakeEngine clusters) get exact, assertable timelines.
- **Bounded**: a deque(maxlen) ring — tracing a busy node costs a dict
  append, never unbounded memory; `dump()` is the observation window.

The thread-local *current context* (`current()`) lets the JSON-lines log
formatter (`utils/logging.py`) tag records with the active trace/span so
logs and traces cross-link.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable

TRACE_KEY = "trace"
DEFAULT_CAPACITY = 4096

_tls = threading.local()


def current() -> tuple[str, str] | None:
    """The thread's active (trace_id, span_id), or None. Set by
    `SpanStore.span()` / `push_ctx()`; read by the JSON log formatter."""
    return getattr(_tls, "ctx", None)


@contextmanager
def push_ctx(trace_id: str, span_id: str):
    """Make (trace_id, span_id) the thread's current context for the
    block — for handlers that adopt a wire context without opening a
    local span."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = (trace_id, span_id)
    try:
        yield
    finally:
        _tls.ctx = prev


# -- wire helpers (the `epoch.py:stamp`/`check_payload` pattern) ----------

def stamp_trace(payload: dict, ctx: tuple[str, str] | None) -> dict:
    """Stamp a payload with a (trace_id, span_id) context, in place
    (returns the payload for chaining). ``ctx=None`` is a no-op so call
    sites never need to branch."""
    if ctx is not None:
        payload[TRACE_KEY] = [ctx[0], ctx[1]]
    return payload


def trace_from_payload(payload) -> tuple[str, str] | None:
    """Extract a (trace_id, parent_span_id) context from a stamped
    payload; None when unstamped (old peer / plain client)."""
    tc = payload.get(TRACE_KEY) if isinstance(payload, dict) else None
    if not tc or len(tc) < 2 or tc[0] is None:
        return None
    return str(tc[0]), str(tc[1])


@dataclass
class Span:
    """One named, timed hop. ``t_end`` is None while open; attrs are
    free-form JSON-safe scalars (shed reason, prefix hit depth, epoch)."""

    trace_id: str
    span_id: str
    parent: str | None
    name: str
    node: str
    t_start: float
    t_end: float | None = None
    attrs: dict = field(default_factory=dict)

    @property
    def ctx(self) -> tuple[str, str]:
        return self.trace_id, self.span_id

    def duration(self) -> float:
        return 0.0 if self.t_end is None else self.t_end - self.t_start

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent": self.parent, "name": self.name, "node": self.node,
                "t_start": round(self.t_start, 6),
                "t_end": (round(self.t_end, 6)
                          if self.t_end is not None else None),
                "attrs": dict(self.attrs)}

    @staticmethod
    def from_wire(d: dict) -> "Span":
        return Span(trace_id=str(d["trace_id"]), span_id=str(d["span_id"]),
                    parent=d.get("parent"), name=str(d["name"]),
                    node=str(d.get("node", "?")),
                    t_start=float(d["t_start"]),
                    t_end=(float(d["t_end"])
                           if d.get("t_end") is not None else None),
                    attrs=dict(d.get("attrs") or {}))


class SpanStore:
    """Per-node bounded span recorder; all methods thread-safe.

    One instance per host (`serve/node.py` hangs it off the Node; the
    chaos cluster builds one per fake host with the shared fake clock).
    Span/trace ids are minted from a node-prefixed counter so they are
    deterministic under seeded simulation and globally unique in a real
    cluster."""

    def __init__(self, node: str, *,
                 clock: Callable[[], float] = time.monotonic,
                 capacity: int = DEFAULT_CAPACITY) -> None:
        self.node = node
        self.clock = clock
        self._lock = threading.Lock()
        self._buf: deque[Span] = deque(maxlen=int(capacity))
        self._seq = 0
        self._recorded = 0            # lifetime total (ring may evict)

    # -- id minting -------------------------------------------------------

    def _next(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    def new_trace(self) -> str:
        return f"t:{self.node}:{self._next()}"

    # -- recording --------------------------------------------------------

    def start(self, name: str, *, trace: str | None = None,
              parent: str | None = None,
              attrs: dict | None = None) -> Span:
        """Open a span (not yet in the buffer — `finish` appends it).
        ``trace=None`` mints a fresh trace rooted at this span."""
        return Span(trace_id=trace or self.new_trace(),
                    span_id=f"{self.node}:{self._next()}", parent=parent,
                    name=name, node=self.node, t_start=self.clock(),
                    attrs=dict(attrs or {}))

    def finish(self, span: Span, **attrs: Any) -> Span:
        span.t_end = self.clock()
        if attrs:
            span.attrs.update(attrs)
        with self._lock:
            self._buf.append(span)
            self._recorded += 1
        return span

    def record(self, name: str, *, trace: str | None = None,
               parent: str | None = None, t_start: float | None = None,
               t_end: float | None = None,
               attrs: dict | None = None) -> Span:
        """One-shot span, appended immediately. Explicit ``t_start``/
        ``t_end`` let callers time against a different clock they own
        (e.g. the gateway's queue-enter timestamp)."""
        now = self.clock()
        span = Span(trace_id=trace or self.new_trace(),
                    span_id=f"{self.node}:{self._next()}", parent=parent,
                    name=name, node=self.node,
                    t_start=now if t_start is None else float(t_start),
                    t_end=now if t_end is None else float(t_end),
                    attrs=dict(attrs or {}))
        with self._lock:
            self._buf.append(span)
            self._recorded += 1
        return span

    @contextmanager
    def span(self, name: str, *, trace: str | None = None,
             parent: str | None = None, attrs: dict | None = None):
        """Timed block; sets the thread-local current context so nested
        logging cross-links. Yields the Span for attr updates."""
        sp = self.start(name, trace=trace, parent=parent, attrs=attrs)
        prev = getattr(_tls, "ctx", None)
        _tls.ctx = sp.ctx
        try:
            yield sp
        finally:
            _tls.ctx = prev
            self.finish(sp)

    # -- observation ------------------------------------------------------

    def dump(self, trace_id: str | None = None,
             limit: int | None = None) -> list[dict]:
        """Wire dicts of the buffered window, oldest first; filtered to
        one trace when ``trace_id`` is given, last ``limit`` otherwise."""
        with self._lock:
            spans = list(self._buf)
        if trace_id is not None:
            spans = [s for s in spans if s.trace_id == trace_id]
        if limit is not None and limit > 0:
            spans = spans[-limit:]
        return [s.to_wire() for s in spans]

    def recorded_total(self) -> int:
        with self._lock:
            return self._recorded

    def depth(self) -> int:
        with self._lock:
            return len(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
