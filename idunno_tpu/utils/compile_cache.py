"""Persistent XLA compile cache.

TPU compiles in this environment go through a remote tunnel (~80 s for the
ResNet-18 forward); the reference's analogue cost — torch.hub model download
+ load on EVERY task (`alexnet_resnet.py:17-22`) — is exactly what the
engine eliminates by keeping weights resident. The compile cache finishes
the job across *processes*: executables land on disk keyed by HLO, so node
restarts and repeat benches skip straight to run.
"""
from __future__ import annotations

import os


def enable_persistent_cache(cache_dir: str | None = None,
                            min_compile_secs: float = 2.0) -> str | None:
    """Point jax at an on-disk compilation cache (idempotent; safe before or
    after backend init). Returns the directory used, or None if the jax
    version has no cache config."""
    import jax

    cache_dir = cache_dir or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
    except Exception:  # noqa: BLE001 - cache is an optimisation, never fatal
        return None
    return cache_dir
